"""Headline benchmark: EC:4 (8+4) Reed-Solomon encode of 1 MiB stripe
blocks on one TPU chip — the hot loop of PutObject (reference:
cmd/erasure-encode.go:69, BASELINE.json configs[1]).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: 25 GiB/s — the AVX512 throughput class of the reference's
klauspost/reedsolomon backend for EC 8+4 on a modern server core-complex
(the reference publishes no absolute numbers, BASELINE.md; klauspost's
own amd64 AVX512 benchmarks land in the 14-30 GiB/s range for these
shapes). vs_baseline > 1 means the TPU path beats AVX512.

Methodology note: the axon tunnel acks dispatches asynchronously and a
host readback costs ~150 ms, so per-call wall timing is useless. We
chain ITERS kernel applications inside one jit (each iteration's input
depends on the previous output) and difference a 1-iteration run from a
(1+ITERS)-iteration run to cancel both the readback latency and the
jit/dispatch constant.
"""

from __future__ import annotations

import json
import time

import numpy as np


BASELINE_GIBPS = 25.0
K, M = 8, 4
BLOCK = 1 << 20            # reference blockSizeV2 (cmd/object-api-common.go:37)
BATCH = 64                 # stripes per device step
ITERS = 200


def _median_time(fn, reps=5):
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    ts.sort()
    return ts[len(ts) // 2]


def main() -> None:
    import jax
    import jax.numpy as jnp

    from minio_tpu.ops import gf256, rs_device

    shard_len = BLOCK // K
    encode = rs_device.make_encoder(gf256.parity_matrix(K, M))

    def chained(n):
        @jax.jit
        def f(x_):
            def body(_, x):
                par = encode(x)
                # Dependency chain: fold one parity byte back into the data
                # so iterations cannot be elided or overlapped.
                return x ^ par[:, :1, :1]
            x_ = jax.lax.fori_loop(0, n, body, x_)
            return x_[0, 0, 0]
        return f

    rng = np.random.default_rng(0)
    data = jnp.asarray(
        rng.integers(0, 256, size=(BATCH, K, shard_len), dtype=np.uint8))

    f1, fn = chained(1), chained(1 + ITERS)
    _ = int(f1(data))      # compile + warm
    _ = int(fn(data))
    t1 = _median_time(lambda: int(f1(data)))
    tn = _median_time(lambda: int(fn(data)))
    per_iter = max((tn - t1) / ITERS, 1e-9)

    data_bytes = BATCH * K * shard_len
    gibps = data_bytes / per_iter / (1 << 30)
    print(json.dumps({
        "metric": "ec_encode_8p4_1mib_gibps_per_chip",
        "value": round(gibps, 2),
        "unit": "GiB/s",
        "vs_baseline": round(gibps / BASELINE_GIBPS, 3),
    }))


if __name__ == "__main__":
    main()
