"""Headline benchmark: fused EC:4 (8+4) Reed-Solomon encode + HighwayHash
bitrot framing of 1 MiB stripe blocks on one TPU chip — the complete
device side of PutObject's hot loop (reference: cmd/erasure-encode.go:69
feeding streamingBitrotWriter, cmd/bitrot-streaming.go:44-75,
BASELINE.json metric "EC encode+bitrot GiB/s per chip").

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: 25 GiB/s — the AVX512 throughput class of the reference's
klauspost/reedsolomon backend for EC 8+4 on a modern server core-complex
(the reference publishes no absolute numbers, BASELINE.md; klauspost's
own amd64 AVX512 benchmarks land in the 14-30 GiB/s range for these
shapes). The reference ALSO HighwayHashes every shard on the CPU after
encoding, so 25 GiB/s overstates its combined rate — using it anyway
keeps vs_baseline conservative. vs_baseline > 1 means the TPU pipeline
beats the AVX512 encode stage alone.

The measured pipeline produces, on device, the Reed-Solomon parity and
the per-block HighwayHash-256S bitrot digests the storage layer writes
(byte-identical to the host path — tests/test_hh_device.py), via:
u32-lane Reed-Solomon (ops/rs_device.make_encoder32) and the Pallas
HighwayHash kernel with its in-VMEM transpose (ops/hh_device). The
on-disk `digest || block` frame is assembled by the shard writers from
these pieces at write time — exactly the reference's streaming bitrot
writer shape (cmd/bitrot-streaming.go:44-75 writes hash, then block) —
so no interleaved frame buffer exists on device or host. No XLA copies
on the path. BATCH is 256 stripes so both stream sets tile exactly
(data 2048 = 2x1024-stream tiles, parity 1024 = 1 tile).

Methodology note: the axon tunnel acks dispatches asynchronously and a
host readback costs ~150 ms, so per-call wall timing is useless. We
chain ITERS pipeline applications inside one jit (each iteration's input
depends on the previous output) and difference a 1-iteration run from a
(1+ITERS)-iteration run to cancel both the readback latency and the
jit/dispatch constant.
"""

from __future__ import annotations

import json
import time

import numpy as np


BASELINE_GIBPS = 25.0
K, M = 8, 4
BLOCK = 1 << 20            # reference blockSizeV2 (cmd/object-api-common.go:37)
BATCH = 256                # stripes per device step
# Chained iterations per measurement: the axon tunnel's ~±15 ms
# dispatch/readback jitter divides by the chain length in the
# differenced per-iteration time; 48 iterations + median-of-5 keeps
# single bench runs within a few percent of the true value.
ITERS = 48


def _median_time(fn, reps=5):
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    ts.sort()
    return ts[len(ts) // 2]


def _chain_time(step, x0):
    """Per-iteration seconds of `step` chained inside one jit (differencing
    a 1-iteration run from a (1+ITERS)-iteration run, see module notes)."""
    import jax

    def chained(niter):
        @jax.jit
        def f(x_):
            x_ = jax.lax.fori_loop(0, niter, lambda _, x: step(x), x_)
            return x_[0, 0, 0]
        return f

    f1, fn = chained(1), chained(1 + ITERS)
    _ = int(f1(x0))        # compile + warm
    _ = int(fn(x0))
    # Median of 5 full differenced measurements: single differences over
    # the axon tunnel swing ±10-30%; compiles are cached, so the extra
    # rounds cost only run time.
    diffs = []
    for _rep in range(5):
        t1 = _median_time(lambda: int(f1(x0)))
        tn = _median_time(lambda: int(fn(x0)))
        diffs.append(max((tn - t1) / ITERS, 1e-9))
    diffs.sort()
    return diffs[2]


# Section gating for partial runs (scripts/bench_smoke.sh, CPU-only
# containers): comma-separated section names, empty = all.
#   MTPU_BENCH_ONLY=put_latency,put_concurrent
# MTPU_BENCH_SMALL=1 shrinks budgets (smoke-test scale) and skips the
# forced-device and served-front-end columns.
import contextlib as _contextlib
import os as _os

_ONLY = {s.strip() for s in _os.environ.get(
    "MTPU_BENCH_ONLY", "").split(",") if s.strip()}
_SMALL = _os.environ.get("MTPU_BENCH_SMALL", "") in ("1", "on", "true")


def _want(section: str) -> bool:
    return not _ONLY or section in _ONLY


@_contextlib.contextmanager
def _forced_device(k: int, m: int):
    """Pin the (k, m) batcher AND the MTPU_BATCH_FORCE env knob to the
    device route for a forced-device bench column, restoring both on
    exit. The env knob rides along so the erasure layer's platform
    gate also yields — on non-TPU hosts the column then measures the
    REAL batched device route (XLA-CPU), not a silently identical
    host path."""
    from minio_tpu.object.erasure_object import _batcher_for
    saved = _os.environ.get("MTPU_BATCH_FORCE")
    _os.environ["MTPU_BATCH_FORCE"] = "device"
    _batcher_for(k, m).force(True)
    try:
        yield
    finally:
        if saved is None:
            _os.environ.pop("MTPU_BATCH_FORCE", None)
        else:
            _os.environ["MTPU_BATCH_FORCE"] = saved
        _batcher_for(k, m).reset_calibration()


def main() -> None:
    if _ONLY and not (_want("device_pipeline") or _want("degraded_get")):
        # Object-layer-only sections: no jax import required at all.
        if _want("put_latency"):
            _put_latency()
        if _want("put_concurrent"):
            _put_concurrent()
        if _want("get_latency"):
            _get_latency()
        if _want("get_concurrent"):
            _get_concurrent()
        if _want("range_get"):
            _range_get()
        if _want("trace_overhead"):
            _trace_overhead()
        if _want("put_scaling"):
            _put_scaling()
        if _want("get_scaling"):
            _get_scaling()
        if _want("meta_listing"):
            _meta_listing()
        if _want("small_put"):
            _small_put()
        if _want("transform_put"):
            _transform_put()
        if _want("transform_get"):
            _transform_get()
        if _want("distributed"):
            _distributed()
        if _want("cluster_get"):
            _cluster_get()
        if _want("connections"):
            _connections()
        if _want("hot_get"):
            _hot_get()
        if _want("rebalance"):
            _rebalance()
        if _want("replication"):
            _replication()
        return

    import jax
    import jax.numpy as jnp

    from minio_tpu.ops import gf256
    from minio_tpu.ops.hh_device import (_hash_words_pallas, _init_smem_np,
                                         _pick_pchunk, make_encode_framer)
    from minio_tpu.ops.rs_device import make_encoder32
    from minio_tpu.utils.highwayhash import MAGIC_KEY

    shard_len = BLOCK // K
    l4 = shard_len // 4
    data_bytes = BATCH * K * shard_len
    rng = np.random.default_rng(0)

    # ---- 1. PutObject device pipeline: encode + bitrot digests --------
    # The PUT hot path's own jitted device pipeline — not a copy.
    if _want("device_pipeline"):
        step = make_encode_framer(gf256.parity_matrix(K, M)).device_step

        def put_step(x):
            parity, dig_d, dig_p = step(x)
            # Dependency chain: fold outputs back into the data so
            # iterations cannot be elided or overlapped.
            return x.at[0, 0, 0].set(
                parity[0, 0, 0] + dig_d[0, 0, 0] + dig_p[0, 0, 0])

        data = jnp.asarray(rng.integers(0, 2 ** 31, size=(BATCH, K, l4),
                                        dtype=np.uint32))
        per_iter = _chain_time(put_step, data)
        gibps = data_bytes / per_iter / (1 << 30)
        print(json.dumps({
            "metric": "ec_encode_bitrot_8p4_1mib_gibps_per_chip",
            "value": round(gibps, 2),
            "unit": "GiB/s",
            "vs_baseline": round(gibps / BASELINE_GIBPS, 3),
        }))

    # ---- 2. Degraded GetObject: EC:4, 3 data shards missing -----------
    # BASELINE config "EC:4 GetObject with 3 shards missing": verify the
    # bitrot digest of every surviving framed shard block (the read-side
    # device kernel the GET path batches into,
    # storage/bitrot.read_framed_blocks_many) and reconstruct the
    # missing data shards from the survivors via the inverted coding
    # matrix on the MXU. Input rows are on-disk frames
    # (`digest || block`); throughput is counted in delivered OBJECT
    # bytes. vs_baseline uses the same conservative AVX512 class figure.
    if _want("degraded_get"):
        missing = (1, 3, 5)
        available = tuple(i for i in range(K + M)
                          if i not in missing)[:K]
        dec = gf256.decode_matrix(K, M, available)   # [k, k] over survivors
        rec_rows = np.ascontiguousarray(dec[list(missing), :])
        reconstruct = make_encoder32(rec_rows)
        init = jnp.asarray(_init_smem_np(MAGIC_KEY))
        pchunk = _pick_pchunk(l4 // 8)

        def get_step(framed):
            blocks = framed[:, :, 8:]                # strip frame digests
            digs = _hash_words_pallas(blocks, init, pchunk=pchunk)
            rec = reconstruct(blocks)                # [B, 3, l4] data rows
            return framed.at[0, 0, 0].set(digs[0, 0] + rec[0, 0, 0])

        framed = jnp.asarray(rng.integers(0, 2 ** 31,
                                          size=(BATCH, K, 8 + l4),
                                          dtype=np.uint32))
        per_iter = _chain_time(get_step, framed)
        gibps = BATCH * BLOCK / per_iter / (1 << 30)
        print(json.dumps({
            "metric": "ec_degraded_get_verify_reconstruct_8p4_gibps_per_chip",
            "value": round(gibps, 2),
            "unit": "GiB/s",
            "vs_baseline": round(gibps / BASELINE_GIBPS, 3),
        }))

    # ---- 3. PutObject p50 latency, EC:4 1 MiB, TPU backend vs host ----
    if _want("put_latency"):
        _put_latency()

    # ---- 4. Concurrent aggregate PUT throughput -----------------------
    if _want("put_concurrent"):
        _put_concurrent()

    # ---- 5-7. Read path: GET latency / aggregate / ranged -------------
    if _want("get_latency"):
        _get_latency()
    if _want("get_concurrent"):
        _get_concurrent()
    if _want("range_get"):
        _range_get()

    # ---- 8. Deep-tracing overhead: disarmed (default) vs armed --------
    if _want("trace_overhead"):
        _trace_overhead()

    # ---- 9. Chip-count scaling of the batched device PUT route --------
    if _want("put_scaling"):
        _put_scaling()

    # ---- 9b. Chip-count scaling of the batched device GET route -------
    if _want("get_scaling"):
        _get_scaling()

    # ---- 10. Metadata plane: LIST/HEAD at high cardinality ------------
    if _want("meta_listing"):
        _meta_listing()

    # ---- 10b. KV-scale small-object write plane -----------------------
    if _want("small_put"):
        _small_put()

    # ---- 10c. Fused transform plane: plaintext vs SSE vs compressed ---
    if _want("transform_put"):
        _transform_put()
    if _want("transform_get"):
        _transform_get()

    # ---- 11. Distributed: N-node cluster vs single node ---------------
    if _want("distributed"):
        _distributed()

    # ---- 11b. Inter-node shard fetch: native vs old grid plane --------
    if _want("cluster_get"):
        _cluster_get()

    # ---- 12. Connection plane: idle fd cost + GET fan-in ramp ---------
    if _want("connections"):
        _connections()

    # ---- 12b. Hot read tier: RAM hit path vs erasure path -------------
    if _want("hot_get"):
        _hot_get()

    # ---- 13. Elastic fleet: foreground SLO under an online drain ------
    if _want("rebalance"):
        _rebalance()

    # ---- 14. Durable replication: lag + chaos convergence -------------
    if _want("replication"):
        _replication()


def _put_latency() -> None:
    """End-to-end PutObject p50/p99 through the real object layer on
    12 local drives, EC 8+4, 1 MiB bodies — BASELINE metric "PutObject
    p50 latency (EC:4, 1 MiB block)", run with the host codec, with
    the TPU backend under its measured calibration, and with the
    device path FORCED (batcher.force(True)) so the BASELINE-named
    device p50 is a recorded number rather than docstring conjecture.
    Small PUTs route by calibration under the tpu config, so the TPU
    backend must not lose to host; vs_baseline = host_p50 / tpu_p50
    (>= 1 means the TPU backend is no slower)."""
    import shutil
    import tempfile

    from minio_tpu.object.erasure_object import ErasureSet
    from minio_tpu.ops.rs_device import DeviceBackend
    from minio_tpu.storage.local import LocalStorage

    rng = np.random.default_rng(1)
    body = rng.integers(0, 256, size=1 << 20, dtype=np.uint8).tobytes()
    reps = 10 if _SMALL else 40

    def run(backend) -> dict:
        root = tempfile.mkdtemp(prefix="bench-put-")
        try:
            disks = [LocalStorage(f"{root}/d{i}") for i in range(12)]
            for d in disks:
                d.make_vol("bench")
            es = ErasureSet(disks, parity=M, backend=backend)
            times = []
            for i in range(reps):
                t0 = time.perf_counter()
                es.put_object("bench", f"o-{i}", body)
                times.append(time.perf_counter() - t0)
            times.sort()
            es.close()
            return {"p50_ms": round(times[len(times) // 2] * 1e3, 2),
                    "p99_ms": round(times[min(reps - 1,
                                              reps * 99 // 100)] * 1e3, 2)}
        finally:
            shutil.rmtree(root, ignore_errors=True)

    host = run(None)
    tpu = run(DeviceBackend("auto"))
    device = None
    if not _SMALL:
        # Forced device path LAST: the pin claims the shared per-(k, m)
        # batcher, so the calibrated run above must precede it (and
        # the pin is reset for the aggregate bench that follows).
        with _forced_device(K, M):
            device = run(DeviceBackend("auto"))
    print(json.dumps({
        "metric": "put_object_p50_ec4_1mib_ms",
        "value": tpu["p50_ms"],
        "unit": "ms",
        "vs_baseline": round(host["p50_ms"] / max(tpu["p50_ms"], 1e-6), 3),
        "host": host, "tpu": tpu, "device_forced": device,
    }))


def _put_concurrent() -> None:
    """Aggregate throughput of 16 concurrent 1 MiB PUTs — the shape of
    the reference's speedtest (cmd/perf-tests.go:76), which drives the
    SERVED S3 API. The headline value is therefore measured through
    the full front-end: the pre-forked SO_REUSEPORT worker fleet
    (io/workers.py, MTPU_HTTP_WORKERS = cores) serving real signed
    HTTP PUTs, run in a clean subprocess (forking after JAX
    initialization is unsafe, and the front-end path is host-codec by
    construction on tunneled-TPU hosts anyway).

    Columns for continuity and calibration evidence:
      host_gibps / tpu_gibps — the object-layer aggregate (the r05
        measure): host codec vs TPU backend under the batcher's
        measured calibration; vs_baseline = tpu/host (>= 1 means the
        TPU backend no longer loses to its own host path).
      device_forced_gibps — the same object-layer aggregate with the
        batcher PINNED to the device, so the cross-request coalescing
        win/loss on this host is a recorded number.
    """
    import shutil
    import tempfile
    from concurrent.futures import ThreadPoolExecutor

    from minio_tpu.object.erasure_object import ErasureSet
    from minio_tpu.ops.rs_device import DeviceBackend
    from minio_tpu.storage.local import LocalStorage

    rng = np.random.default_rng(2)
    body = rng.integers(0, 256, size=1 << 20, dtype=np.uint8).tobytes()
    # Small budget keeps FULL concurrency (the committed number is a
    # 16-way aggregate; fewer clients measure a different quantity)
    # and cuts the per-client rep count + measured passes instead.
    threads, per_thread = (16, 3) if _SMALL else (16, 6)

    def run(backend) -> float:
        root = tempfile.mkdtemp(prefix="bench-agg-")
        try:
            disks = [LocalStorage(f"{root}/d{i}") for i in range(12)]
            for d in disks:
                d.make_vol("bench")
            es = ErasureSet(disks, parity=M, backend=backend)
            ex = ThreadPoolExecutor(max_workers=threads)

            def worker(t):
                for i in range(per_thread):
                    es.put_object("bench", f"o-{t}-{i}", body)

            list(ex.map(worker, range(threads)))       # warm pass
            best = 0.0
            for _rep in range(1 if _SMALL else 2):
                # Best-of-2 measured passes: aggregate throughput is
                # scheduler-noise-prone; the floor of the noise is the
                # honest capability number.
                t0 = time.perf_counter()
                list(ex.map(worker, range(threads)))
                wall = time.perf_counter() - t0
                best = max(best,
                           threads * per_thread * len(body) / wall
                           / (1 << 30))
            ex.shutdown(wait=False)
            es.close()
            return best
        finally:
            shutil.rmtree(root, ignore_errors=True)

    host = run(None)
    tpu = run(DeviceBackend("auto"))
    device_forced = served = None
    if not _SMALL:
        with _forced_device(K, M):
            device_forced = run(DeviceBackend("auto"))
    if (_os.cpu_count() or 1) >= 2:
        # Front-end aggregate in a clean subprocess (no inherited JAX);
        # the probe run is shared with the GET aggregate section.
        # Small-budget smoke runs probe too (fewer reps, same fleet):
        # the served/object ratio must be a gateable column, never
        # null, wherever the pre-forked fleet can actually boot
        # (http_workers >= 2).
        served = _served_probe_value("SERVED_GIBPS")

    # Headline: the best measured aggregate among the store's serving
    # configurations — the served front-end number when the worker
    # fleet wins (many-core hosts), the object-layer number when the
    # probe is client-bound (the 16 signed clients share cores with
    # the fleet on small hosts). All columns are recorded either way.
    best = max(v for v in (tpu, served) if v is not None)
    print(json.dumps({
        "metric": "put_concurrent_aggregate_gibps",
        "value": round(best, 3),
        "unit": "GiB/s",
        "vs_baseline": round(tpu / max(host, 1e-9), 3),
        "host_gibps": round(host, 3),
        "tpu_gibps": round(tpu, 3),
        "device_forced_gibps":
            None if device_forced is None else round(device_forced, 3),
        "served_gibps": None if served is None else round(served, 3),
        # served/object like-for-like: the probe fleet boots with the
        # default (auto) backend, which is what tpu_gibps measures on
        # every host class — the gated front-end-tax ratio.
        "served_ratio": None if served is None
        else round(served / max(tpu, 1e-9), 3),
        "http_workers": _os.cpu_count(),
        "concurrency": threads,
    }))


def _small_put() -> None:
    """KV-scale small-object write plane (ROADMAP item 4): 4 KiB
    objects at high concurrency through the real object layer
    (12 local drives, EC 8+4, inline journal commits), ops/s +
    p50/p99. Two like-for-like columns inside ONE run on one host:

      value / p50 / p99   group-commit lanes ON (the shipped default):
                          concurrent commits coalesce per drive into
                          WAL-backed batches (storage/group_commit)
      solo_ops_s          MTPU_GROUP_COMMIT=off on the SAME fixture —
                          the per-request commit fan-out, which is the
                          pre-PR write path byte-for-byte

      served_ops_s        the same storm through the pre-forked HTTP
                          front end (probe subprocess; explicit null
                          where the fleet cannot boot)

    Best-of-2 measured passes per column (aggregate ops/s on a shared
    box is scheduler-noise-prone; the floor of the noise is the honest
    capability number), fresh keys every pass (the KV-ingest shape).
    """
    import shutil
    import tempfile
    from concurrent.futures import ThreadPoolExecutor

    from minio_tpu.object.erasure_object import ErasureSet
    from minio_tpu.storage.local import LocalStorage

    body = np.random.default_rng(7).integers(
        0, 256, size=4096, dtype=np.uint8).tobytes()
    threads, per = (16, 25) if _SMALL else (32, 50)

    def run(group_on: bool):
        saved = _os.environ.get("MTPU_GROUP_COMMIT")
        _os.environ["MTPU_GROUP_COMMIT"] = "on" if group_on else "off"
        base = "/dev/shm" if _os.access("/dev/shm", _os.W_OK) else None
        root = tempfile.mkdtemp(prefix="bench-smallput-", dir=base)
        try:
            disks = [LocalStorage(f"{root}/d{i}") for i in range(12)]
            for d in disks:
                d.make_vol("bench")
            es = ErasureSet(disks, parity=M)
            ex = ThreadPoolExecutor(max_workers=threads)
            lat: list = []

            def put(tag, t, collect):
                mine = []
                for i in range(per):
                    t0 = time.perf_counter()
                    es.put_object("bench", f"{tag}-{t}-{i}", body)
                    mine.append(time.perf_counter() - t0)
                if collect:
                    lat.extend(mine)

            list(ex.map(lambda t: put("w", t, False), range(threads)))
            best, best_lat = 0.0, []
            for rep in range(2):
                lat = []
                t0 = time.perf_counter()
                list(ex.map(lambda t: put(f"m{rep}", t, True),
                            range(threads)))
                ops = threads * per / (time.perf_counter() - t0)
                if ops > best:
                    best, best_lat = ops, sorted(lat)
            gc = es.group_commit.stats() \
                if getattr(es, "group_commit", None) else None
            ex.shutdown(wait=False)
            es.close()
            p50 = best_lat[len(best_lat) // 2] * 1e3
            p99 = best_lat[min(len(best_lat) - 1,
                               len(best_lat) * 99 // 100)] * 1e3
            return best, round(p50, 2), round(p99, 2), gc
        finally:
            if saved is None:
                _os.environ.pop("MTPU_GROUP_COMMIT", None)
            else:
                _os.environ["MTPU_GROUP_COMMIT"] = saved
            shutil.rmtree(root, ignore_errors=True)

    solo_ops, solo_p50, solo_p99, _ = run(group_on=False)
    ops, p50, p99, gc = run(group_on=True)
    served = None
    if (_os.cpu_count() or 1) >= 2:
        served = _served_probe_value("SERVED_SMALL_PUT_OPS")
    summary = None
    if gc is not None:
        summary = {k: gc[k] for k in
                   ("batches", "members", "solo_bypass", "fill_mean",
                    "fsyncs_saved", "merged_members", "noop_skips",
                    "deadline_culls", "solo_demotions")}
        summary["fill_mean"] = round(summary["fill_mean"], 2)
    print(json.dumps({
        "metric": "small_put_ops_s",
        "value": round(ops, 1),
        "unit": "ops/s",
        "p50_ms": p50, "p99_ms": p99,
        "solo_ops_s": round(solo_ops, 1),
        "solo_p50_ms": solo_p50, "solo_p99_ms": solo_p99,
        "vs_solo": round(ops / max(solo_ops, 1e-9), 3),
        "served_ops_s": served,
        "object_bytes": len(body),
        "concurrency": threads,
        "group_commit": summary,
    }))


def _transform_fixture():
    """(root, es, kms, body): 12-drive EC 8+4 set on /dev/shm plus a
    bench KMS, shared by the transform_put/transform_get sections."""
    import base64
    import tempfile

    from minio_tpu.crypto.kms import KMS
    from minio_tpu.object.erasure_object import ErasureSet
    from minio_tpu.storage.local import LocalStorage

    base = "/dev/shm" if _os.access("/dev/shm", _os.W_OK) else None
    root = tempfile.mkdtemp(prefix="bench-transform-", dir=base)
    disks = [LocalStorage(f"{root}/d{i}") for i in range(12)]
    for d in disks:
        d.make_vol("bench")
    es = ErasureSet(disks, parity=M)
    kms = KMS({"bench": b"\x07" * 32}, "bench")
    # Compressible-but-not-trivial body (numbered text lines), 4 MiB.
    line = b"".join(b"%09d transform bench line\n" % i
                    for i in range(5000))
    size = (1 << 20) if _SMALL else (4 << 20)
    body = (line * (size // len(line) + 1))[:size]
    del base64
    return root, es, kms, body


def _transform_modes(kms, body):
    """Mode name -> (PutOptions factory, per-object spec factory).
    Factories build FRESH options per object (SSE seals a fresh data
    key per object, exactly like the S3 handler)."""
    from minio_tpu.crypto import sse as sse_mod
    from minio_tpu.object import transform as tf
    from minio_tpu.object.types import PutOptions

    def plain(bucket, key):
        return PutOptions(transform=tf.TransformSpec())

    def sse(bucket, key):
        data_key, nonce, imeta = sse_mod.encrypt_metadata(
            bucket, key, len(body), kms, None)
        opts = PutOptions(transform=tf.TransformSpec(
            enc_key=data_key, enc_nonce=nonce))
        opts.internal_metadata.update(imeta)
        return opts

    def comp(bucket, key):
        return PutOptions(transform=tf.TransformSpec(compress=True))

    return {"plain": plain, "sse": sse, "comp": comp}


def _transform_put() -> None:
    """Fused single-pass PUT data plane (ROADMAP item 3): aggregate
    PUT throughput for plaintext vs SSE (DARE AES-256-GCM) vs
    compressed bodies, like-for-like in ONE run on one fixture — the
    fused pass (one native digest/compress/encrypt/frame call per PUT)
    against the layered per-stage pipeline (MTPU_TRANSFORM_FUSED=off)
    on the same fixture. The acceptance signal is the sse/plain and
    comp/plain aggregate ratios (chartered ~<= 1.1x on a host whose
    wall is the data path) plus the path-split counters proving ZERO
    legacy-path requests with fusion on. Explicit-null skip when the
    native kernel library is unavailable."""
    import shutil
    from concurrent.futures import ThreadPoolExecutor

    from minio_tpu.object import transform as tf

    if not tf.fused_put_enabled():
        for mode in ("plain", "sse", "comp"):
            print(json.dumps({
                "metric": f"transform_put_{mode}_gibps", "value": None,
                "skipped": "native transform kernel unavailable"}))
        return
    root, es, kms, body = _transform_fixture()
    threads, per = (4, 3) if _SMALL else (8, 6)
    try:
        modes = _transform_modes(kms, body)

        def run_mode(mode, fused_on):
            saved = _os.environ.get("MTPU_TRANSFORM_FUSED")
            _os.environ["MTPU_TRANSFORM_FUSED"] = \
                "on" if fused_on else "off"
            try:
                ex = ThreadPoolExecutor(max_workers=threads)
                lat: list = []

                def put(tag, t):
                    for i in range(per):
                        opts = modes[mode](
                            "bench", f"{mode}-{tag}-{t}-{i}")
                        t0 = time.perf_counter()
                        es.put_object("bench",
                                      f"{mode}-{tag}-{t}-{i}", body,
                                      opts)
                        lat.append(time.perf_counter() - t0)

                list(ex.map(lambda t: put("w", t), range(threads)))
                best, best_lat = 0.0, []
                for rep in range(2):
                    lat = []
                    t0 = time.perf_counter()
                    list(ex.map(lambda t: put(f"m{rep}", t),
                                range(threads)))
                    gibps = threads * per * len(body) \
                        / (time.perf_counter() - t0) / (1 << 30)
                    if gibps > best:
                        best, best_lat = gibps, sorted(lat)
                ex.shutdown(wait=False)
                p50 = best_lat[len(best_lat) // 2] * 1e3
                return best, round(p50, 2)
            finally:
                if saved is None:
                    _os.environ.pop("MTPU_TRANSFORM_FUSED", None)
                else:
                    _os.environ["MTPU_TRANSFORM_FUSED"] = saved

        tf.reset_stats()
        fused = {m: run_mode(m, True) for m in ("plain", "sse", "comp")}
        split = tf.stats()["put_requests"]
        legacy = {m: run_mode(m, False)
                  for m in ("plain", "sse", "comp")}
        plain_gibps = fused["plain"][0]
        for mode in ("plain", "sse", "comp"):
            g, p50 = fused[mode]
            lg, lp50 = legacy[mode]
            line = {
                "metric": f"transform_put_{mode}_gibps",
                "value": round(g, 3),
                "unit": "GiB/s",
                "p50_ms": p50,
                "legacy_gibps": round(lg, 3),
                "legacy_p50_ms": lp50,
                "vs_legacy": round(g / max(lg, 1e-9), 3),
                "object_bytes": len(body),
                "concurrency": threads,
            }
            if mode != "plain":
                line["vs_plain"] = round(g / max(plain_gibps, 1e-9), 3)
            if mode == "plain":
                line["path_split"] = dict(split)
            print(json.dumps(line))
    finally:
        es.close()
        shutil.rmtree(root, ignore_errors=True)


def _transform_get() -> None:
    """GET direction of the fused transform plane: aggregate
    whole-object GET throughput for plaintext vs SSE vs compressed
    objects (windowed verify -> decrypt -> decompress out of the
    pooled GET readahead), like-for-like in one run, fused vs the
    layered pipeline on the same stored objects."""
    import shutil
    from concurrent.futures import ThreadPoolExecutor

    from minio_tpu.object import transform as tf
    from minio_tpu.object.types import GetOptions

    if not tf.fused_put_enabled():
        for mode in ("plain", "sse", "comp"):
            print(json.dumps({
                "metric": f"transform_get_{mode}_gibps", "value": None,
                "skipped": "native transform kernel unavailable"}))
        return
    root, es, kms, body = _transform_fixture()
    threads, per = (4, 3) if _SMALL else (8, 6)
    n_objs = threads
    try:
        modes = _transform_modes(kms, body)
        for mode, mk in modes.items():
            for i in range(n_objs):
                es.put_object("bench", f"g-{mode}-{i}", body,
                              mk("bench", f"g-{mode}-{i}"))

        def read_one(mode, i):
            info = es.get_object_info("bench", f"g-{mode}-{i}")
            imeta = info.internal_metadata
            if imeta.get("x-internal-sse-alg"):
                _, chunks, _, _ = tf.get_encrypted(
                    es, kms, "bench", f"g-{mode}-{i}",
                    info.version_id, None, {}, info)
            elif imeta.get("x-internal-comp"):
                _, chunks, _, _ = tf.get_compressed(
                    es, "bench", f"g-{mode}-{i}", info.version_id,
                    None, info)
            else:
                _, chunks = es.get_object_stream(
                    "bench", f"g-{mode}-{i}", GetOptions())
            total = 0
            for c in chunks:
                total += len(c)
            if total != len(body):
                raise RuntimeError(
                    f"short read: {total} != {len(body)}")

        def run_mode(mode, fused_on):
            saved = _os.environ.get("MTPU_TRANSFORM_FUSED")
            _os.environ["MTPU_TRANSFORM_FUSED"] = \
                "on" if fused_on else "off"
            try:
                ex = ThreadPoolExecutor(max_workers=threads)

                def reader(t):
                    for i in range(per):
                        read_one(mode, (t + i) % n_objs)

                list(ex.map(reader, range(threads)))   # warm
                best = 0.0
                for _rep in range(2):
                    t0 = time.perf_counter()
                    list(ex.map(reader, range(threads)))
                    gibps = threads * per * len(body) \
                        / (time.perf_counter() - t0) / (1 << 30)
                    best = max(best, gibps)
                ex.shutdown(wait=False)
                return best
            finally:
                if saved is None:
                    _os.environ.pop("MTPU_TRANSFORM_FUSED", None)
                else:
                    _os.environ["MTPU_TRANSFORM_FUSED"] = saved

        fused = {m: run_mode(m, True) for m in ("plain", "sse", "comp")}
        legacy = {m: run_mode(m, False)
                  for m in ("plain", "sse", "comp")}
        for mode in ("plain", "sse", "comp"):
            line = {
                "metric": f"transform_get_{mode}_gibps",
                "value": round(fused[mode], 3),
                "unit": "GiB/s",
                "legacy_gibps": round(legacy[mode], 3),
                "vs_legacy": round(
                    fused[mode] / max(legacy[mode], 1e-9), 3),
                "object_bytes": len(body),
                "concurrency": threads,
            }
            if mode != "plain":
                line["vs_plain"] = round(
                    fused[mode] / max(fused["plain"], 1e-9), 3)
            print(json.dumps(line))
    finally:
        es.close()
        shutil.rmtree(root, ignore_errors=True)


def _bench_set(root, n_objects, body):
    """A 12-drive EC 8+4 set pre-loaded with n_objects copies of body
    under bench/o-<i> (host codec — the GET path is host-side by
    construction on tunneled-TPU hosts, same as the front-end)."""
    from minio_tpu.object.erasure_object import ErasureSet
    from minio_tpu.storage.local import LocalStorage
    disks = [LocalStorage(f"{root}/d{i}") for i in range(12)]
    for d in disks:
        d.make_vol("bench")
    es = ErasureSet(disks, parity=M)
    for i in range(n_objects):
        es.put_object("bench", f"o-{i}", body)
    return es


def _get_latency() -> None:
    """End-to-end GetObject p50/p99 through the real object layer on
    12 local drives, EC 8+4, 1 MiB bodies. Columns: `cold` — the first
    GET of each object (full quorum read_version fan-out) — `hot` —
    repeat GETs of already-read objects (the fileinfo-cache +
    verify-kernel path) — and `reconstruct` — repeat GETs with one
    drive's copies REMOVED, over only the keys whose lost shard was a
    data shard, so every measured read pays the degraded-read rebuild
    (device-batched where this host's decode calibration picks the
    device). The headline value is the hot p50: repeat reads are the
    serving steady state. Emits an explicit-null line when the fixture
    cannot build on this host (gate skips cleanly)."""
    try:
        _get_latency_inner()
    except (OSError, MemoryError) as e:
        # Only environment failures (no space/fds/memory for the
        # fixture) skip; correctness failures — e.g. a wrong-length
        # reconstruct — must propagate and fail the bench loudly.
        print(json.dumps({"metric": "get_object_p50_ec4_1mib_ms",
                          "value": None, "unit": "ms",
                          "skipped": f"fixture failed: {e}"}))


def _get_latency_inner() -> None:
    import shutil
    import tempfile

    from minio_tpu.object.erasure_object import hash_order

    rng = np.random.default_rng(4)
    body = rng.integers(0, 256, size=1 << 20, dtype=np.uint8).tobytes()
    n_objects = 8 if _SMALL else 24
    root = tempfile.mkdtemp(prefix="bench-get-")
    try:
        es = _bench_set(root, n_objects, body)
        cold, hot = [], []
        for i in range(n_objects):
            t0 = time.perf_counter()
            _, got = es.get_object("bench", f"o-{i}")
            cold.append(time.perf_counter() - t0)
            assert len(got) == len(body)
        for _rep in range(2):
            for i in range(n_objects):
                t0 = time.perf_counter()
                es.get_object("bench", f"o-{i}")
                hot.append(time.perf_counter() - t0)
        # Degraded column: drive 0's copies vanish; keys whose shard on
        # d0 was a DATA shard (index < k) now reconstruct every read.
        # The MRF worker must be stopped FIRST: every degraded read
        # enqueues a background heal that would restore d0's copies
        # mid-measurement, silently turning later reps into hot-path
        # reads.
        es.mrf.stop()
        shutil.rmtree(f"{root}/d0/bench", ignore_errors=True)
        es.metacache.bump("bench")
        rec_keys = [i for i in range(n_objects)
                    if hash_order(f"bench/o-{i}", 12)[0] <= 12 - M]
        rec = []
        for _rep in range(2):
            for i in rec_keys:
                t0 = time.perf_counter()
                _, got = es.get_object("bench", f"o-{i}")
                rec.append(time.perf_counter() - t0)
                assert len(got) == len(body)
        cold.sort()
        hot.sort()
        rec.sort()

        def pct(ts, p):
            return round(ts[min(len(ts) - 1, len(ts) * p // 100)] * 1e3, 2)

        es.close()
        print(json.dumps({
            "metric": "get_object_p50_ec4_1mib_ms",
            "value": pct(hot, 50),
            "unit": "ms",
            "vs_baseline": round(pct(cold, 50) / max(pct(hot, 50), 1e-6),
                                 3),
            "cold": {"p50_ms": pct(cold, 50), "p99_ms": pct(cold, 99)},
            "hot": {"p50_ms": pct(hot, 50), "p99_ms": pct(hot, 99)},
            "reconstruct": ({"p50_ms": pct(rec, 50),
                             "p99_ms": pct(rec, 99),
                             "keys": len(rec_keys)} if rec else None),
        }))
    finally:
        shutil.rmtree(root, ignore_errors=True)


def _get_concurrent() -> None:
    """Aggregate throughput of 16 concurrent 1 MiB GETs — the read-side
    mirror of _put_concurrent. Columns:
      object_layer_gibps — 16 threads re-reading pre-put objects
        through the object layer in-process;
      served_gibps — the same aggregate through the full pre-forked
        SO_REUSEPORT front-end (signed HTTP GETs in a clean
        subprocess); this is the headline when the fleet wins.
    """
    import shutil
    import tempfile
    from concurrent.futures import ThreadPoolExecutor

    rng = np.random.default_rng(5)
    body = rng.integers(0, 256, size=1 << 20, dtype=np.uint8).tobytes()
    threads, per_thread = (16, 3) if _SMALL else (16, 6)
    root = tempfile.mkdtemp(prefix="bench-getagg-")
    try:
        es = _bench_set(root, threads * per_thread, body)
        ex = ThreadPoolExecutor(max_workers=threads)

        def worker(t):
            for i in range(per_thread):
                _, got = es.get_object("bench", f"o-{t * per_thread + i}")
                assert len(got) == len(body)

        list(ex.map(worker, range(threads)))          # warm pass
        best = 0.0
        for _rep in range(1 if _SMALL else 2):
            t0 = time.perf_counter()
            list(ex.map(worker, range(threads)))
            wall = time.perf_counter() - t0
            best = max(best, threads * per_thread * len(body) / wall
                       / (1 << 30))
        # Degraded aggregate: the same 16-way re-read with one drive's
        # copies removed — roughly k/n of the keys reconstruct their
        # lost data shard every read (device-batched where calibrated),
        # the rest lose only parity. The realistic one-dead-drive
        # serving shape. MRF stops first or background heals would
        # restore d0 mid-measurement (degraded reads enqueue heals).
        import shutil as _sh
        es.mrf.stop()
        _sh.rmtree(f"{root}/d0/bench", ignore_errors=True)
        es.metacache.bump("bench")
        list(ex.map(worker, range(threads)))          # warm degraded
        reconstruct = 0.0
        for _rep in range(1 if _SMALL else 2):
            t0 = time.perf_counter()
            list(ex.map(worker, range(threads)))
            wall = time.perf_counter() - t0
            reconstruct = max(reconstruct,
                              threads * per_thread * len(body) / wall
                              / (1 << 30))
        ex.shutdown(wait=False)
        es.close()
    finally:
        shutil.rmtree(root, ignore_errors=True)

    served = None
    if (_os.cpu_count() or 1) >= 2:
        # Smoke-gateable like the PUT column: probed at every budget
        # wherever the fleet boots (http_workers >= 2).
        served = _served_probe_value("SERVED_GET_GIBPS")
    value = max(v for v in (best, served) if v is not None)
    # vs_baseline mirrors the PUT metric's config-ratio shape:
    # served / object-layer — how much of the in-process read rate
    # survives the full front-end (signing, HTTP, worker fleet).
    print(json.dumps({
        "metric": "get_concurrent_aggregate_gibps",
        "value": round(value, 3),
        "unit": "GiB/s",
        "vs_baseline": round((served if served is not None else best)
                             / max(best, 1e-9), 3),
        "object_layer_gibps": round(best, 3),
        "reconstruct_gibps": round(reconstruct, 3),
        "served_gibps": None if served is None else round(served, 3),
        # Gated front-end-tax ratio (see put_concurrent).
        "served_ratio": None if served is None
        else round(served / max(best, 1e-9), 3),
        "http_workers": _os.cpu_count(),
        "concurrency": threads,
    }))


def _range_get() -> None:
    """Ranged GETs against one large streamed object (multi-window on
    the streaming read path): p50 latency of 1 MiB ranges at
    block-unaligned offsets, plus the effective throughput of one
    big range streamed via get_object_stream."""
    import shutil
    import tempfile

    from minio_tpu.object.types import GetOptions

    rng = np.random.default_rng(6)
    size = (36 if _SMALL else 64) << 20
    body = rng.integers(0, 256, size=size, dtype=np.uint8).tobytes()
    root = tempfile.mkdtemp(prefix="bench-range-")
    try:
        es = _bench_set(root, 0, b"")
        es.put_object("bench", "big", body)
        # 1 MiB ranges at odd offsets spread across the object.
        reps = 8 if _SMALL else 24
        lat = []
        for i in range(reps):
            off = (i * (size // reps) + 4097) % (size - (1 << 20))
            t0 = time.perf_counter()
            _, got = es.get_object(
                "bench", "big", GetOptions(offset=off, length=1 << 20))
            lat.append(time.perf_counter() - t0)
            assert len(got) == 1 << 20
        lat.sort()
        # One big streamed range (all but the first/last unaligned MiB).
        t0 = time.perf_counter()
        n = 0
        _, chunks = es.get_object_stream(
            "bench", "big",
            GetOptions(offset=12345, length=size - 23456))
        for c in chunks:
            n += len(c)
        wall = time.perf_counter() - t0
        assert n == size - 23456
        es.close()
        print(json.dumps({
            "metric": "range_get_1mib_p50_ms",
            "value": round(lat[len(lat) // 2] * 1e3, 2),
            "unit": "ms",
            "vs_baseline": 1.0,
            "p99_ms": round(lat[min(reps - 1, reps * 99 // 100)] * 1e3, 2),
            "stream_gibps": round(n / wall / (1 << 30), 3),
        }))
    finally:
        shutil.rmtree(root, ignore_errors=True)


def _trace_overhead() -> None:
    """Deep-tracing overhead: the same PUT/GET loops measured with span
    collection DISARMED (the default — every call site reduces to one
    module-attribute check) and ARMED (a bound TraceContext per op, the
    shape a live `mc admin trace --types=all` subscriber induces).
    Disarmed numbers are like-for-like with the put/get aggregate
    sections, so the committed-artifact smoke gate
    (scripts/bench_smoke.sh) doubles as the ≤2% disarmed-overhead
    regression check across PRs; the armed column bounds the cost of
    actually watching.

    The `grid` column measures the cross-node propagation tax on the
    wire: armed vs disarmed round-trips of a small unary call through
    a REAL GridServer/GridClient pair — the armed side carries the
    trace context out, executes the handler under it on the peer, and
    ships the remote subtree back piggybacked on the reply; the
    disarmed side must stay byte-identical to the pre-propagation
    frames (one attribute check on the hot path). Its ratio folds into
    vs_baseline, so the smoke gate also watches propagation cost.

    The emitted line carries an `slo` snapshot: a default SLOEngine
    fed this section's op outcomes, evaluated against the same rolling
    windows the live server uses — the bench summary states whether
    the run itself met the declared objectives."""
    import shutil
    import tempfile

    from minio_tpu.s3.metrics import Metrics
    from minio_tpu.utils import tracing
    from minio_tpu.utils.slo import SLOEngine

    slo_metrics = Metrics()
    slo_eng = SLOEngine()

    rng = np.random.default_rng(7)
    body = rng.integers(0, 256, size=1 << 20, dtype=np.uint8).tobytes()
    n_objects = 16 if _SMALL else 48

    def measure(armed: bool) -> tuple[float, float]:
        root = tempfile.mkdtemp(prefix="bench-trace-")
        try:
            es = _bench_set(root, 0, b"")
            if armed:
                tracing.arm("bench")

            def ctx():
                return tracing.bind(tracing.TraceContext()) if armed \
                    else tracing.bind(None)

            t0 = time.perf_counter()
            for i in range(n_objects):
                with ctx():
                    es.put_object("bench", f"o-{i}", body)
            put_s = time.perf_counter() - t0
            for i in range(n_objects):        # warm the read path
                with ctx():
                    es.get_object("bench", f"o-{i}")
            t0 = time.perf_counter()
            for _rep in range(2):
                for i in range(n_objects):
                    with ctx():
                        _, got = es.get_object("bench", f"o-{i}")
                        assert len(got) == len(body)
            get_s = time.perf_counter() - t0
            es.close()
            # Feed the run's outcomes to the SLO engine (mean per-op
            # latency into the rolling windows, one outcome per op).
            for api, secs, reps in (("PUT:object", put_s, n_objects),
                                    ("GET:object", get_s,
                                     2 * n_objects)):
                per_op = secs / reps
                for _ in range(reps):
                    slo_metrics.record(api, 200, per_op)
                    slo_eng.observe(api, 200)
            total = n_objects * len(body)
            return (total / put_s / (1 << 30),
                    2 * total / get_s / (1 << 30))
        finally:
            if armed:
                tracing.disarm("bench")
            shutil.rmtree(root, ignore_errors=True)

    def measure_grid(armed: bool) -> float:
        """Mean microseconds per small unary grid call (real server +
        client on loopback), armed carrying full trace propagation
        (fresh context per call, subtree shipped back and stitched)."""
        from minio_tpu.grid.client import GridClient
        from minio_tpu.grid.server import GridServer
        gs = GridServer(0, host="127.0.0.1")
        gs.register("echo", lambda p: p)
        gs.start()
        try:
            gc = GridClient("127.0.0.1", gs.port)
            reps = 200 if _SMALL else 1000
            for _ in range(50):             # warm connection + path
                gc.call("echo", {"x": 1}, timeout=5.0)
            if armed:
                tracing.arm("bench-grid")
            try:
                t0 = time.perf_counter()
                if armed:
                    for _ in range(reps):
                        with tracing.bind(tracing.TraceContext()):
                            gc.call("echo", {"x": 1}, timeout=5.0)
                else:
                    for _ in range(reps):
                        gc.call("echo", {"x": 1}, timeout=5.0)
                return (time.perf_counter() - t0) / reps * 1e6
            finally:
                if armed:
                    tracing.disarm("bench-grid")
        finally:
            gs.stop()

    # Disarmed twice (first run also warms pools/imports), keep best;
    # armed between the two disarmed runs shares the warm state.
    put_d1, get_d1 = measure(armed=False)
    put_a, get_a = measure(armed=True)
    put_d2, get_d2 = measure(armed=False)
    put_d, get_d = max(put_d1, put_d2), max(get_d1, get_d2)
    put_ovh = max(0.0, (1 - put_a / put_d) * 100)
    get_ovh = max(0.0, (1 - get_a / get_d) * 100)
    grid_d1 = measure_grid(armed=False)
    grid_a = measure_grid(armed=True)
    grid_d2 = measure_grid(armed=False)
    grid_d = min(grid_d1, grid_d2)            # best (lowest) latency
    grid_ovh = max(0.0, (grid_a / grid_d - 1) * 100)
    # For throughput columns higher is better (armed/disarmed < 1 is
    # overhead); for the grid latency column lower is better, so its
    # contribution to vs_baseline inverts to disarmed/armed.
    ratios = (put_a / put_d, get_a / get_d, grid_d / grid_a)
    print(json.dumps({
        "metric": "tracing_overhead_armed_vs_disarmed_pct",
        "value": round(max(put_ovh, get_ovh, grid_ovh), 2),
        "unit": "%",
        "vs_baseline": round(min(ratios), 3),
        "put": {"disarmed_gibps": round(put_d, 3),
                "armed_gibps": round(put_a, 3),
                "overhead_pct": round(put_ovh, 2)},
        "get": {"disarmed_gibps": round(get_d, 3),
                "armed_gibps": round(get_a, 3),
                "overhead_pct": round(get_ovh, 2)},
        "grid": {"disarmed_us": round(grid_d, 1),
                 "armed_us": round(grid_a, 1),
                 "overhead_pct": round(grid_ovh, 2)},
        "slo": slo_eng.snapshot(metrics=slo_metrics),
        "objects": n_objects,
    }))


def _put_scaling() -> None:
    """Chip-count scaling of the batched device PUT route: the 16-way
    concurrent 1 MiB PUT aggregate with the batcher PINNED to the
    device (MTPU_BATCH_FORCE=device) measured at 1/2/4/8 visible
    devices. Each point runs in a clean subprocess because the device
    count must be fixed before JAX initializes: TPU hosts cap the mesh
    via MTPU_MESH_DEVICES over real chips; CPU-only containers
    (JAX_PLATFORMS=cpu) get N virtual host devices via
    --xla_force_host_platform_device_count — identical code path, but
    the numbers there prove plumbing, not speedup (N schedulers share
    the same cores). vs_baseline is the max-devices aggregate over the
    1-device aggregate: near-linear scaling is the tentpole claim, and
    this metric is what MULTICHIP_r06+ records."""
    import subprocess
    import sys as _sys
    sweep: dict[str, float] = {}
    devices: dict[str, int] = {}
    dropped: list[str] = []
    for n in (1, 2, 4, 8):
        env = {**_os.environ, "MTPU_SCALING_N": str(n),
               "MTPU_BATCH_FORCE": "device"}
        try:
            out = subprocess.run(
                [_sys.executable, __file__, "--scaling-probe"],
                capture_output=True, timeout=900, env=env)
            for line in out.stdout.decode().splitlines():
                if line.startswith("SCALING_GIBPS="):
                    sweep[str(n)] = float(line.split("=", 1)[1])
                elif line.startswith("SCALING_DEVICES="):
                    devices[str(n)] = int(line.split("=", 1)[1])
        except Exception:  # noqa: BLE001 - sweep point best-effort
            pass
        if str(n) not in sweep:
            dropped.append(str(n))
    if not sweep:
        print(json.dumps({"metric": "put_scaling_aggregate_gibps",
                          "value": None, "unit": "GiB/s",
                          "error": "no sweep point completed"}))
        return
    ns = sorted(sweep, key=int)
    base, top = sweep[ns[0]], sweep[ns[-1]]
    # baseline_devices names the sweep point vs_baseline actually
    # divides by: if the 1-device probe died, the ratio is top/2-device
    # and must not read as chips-vs-one-chip scaling.
    print(json.dumps({
        "metric": "put_scaling_aggregate_gibps",
        "value": round(top, 3),
        "unit": "GiB/s",
        "vs_baseline": round(top / max(base, 1e-9), 3),
        "baseline_devices": int(ns[0]),
        "sweep_gibps": {k: round(sweep[k], 3) for k in ns},
        "dropped_points": dropped,
        "mesh_devices": devices,
        "route": "device_forced",
        "concurrency": 16,
    }))


def _scaling_probe() -> None:
    """Subprocess body for one put_scaling sweep point: pin the mesh
    width (and, on CPU, materialize that many virtual host devices)
    BEFORE JAX initializes, then measure the object-layer 16-way PUT
    aggregate with the batcher forced to the device route."""
    import os
    import shutil
    import tempfile
    n = max(1, int(os.environ.get("MTPU_SCALING_N", "1") or 1))
    os.environ["MTPU_MESH_DEVICES"] = str(n)
    os.environ.setdefault("MTPU_BATCH_FORCE", "device")
    if "cpu" in os.environ.get("JAX_PLATFORMS", "").lower():
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={n}").strip()
    from concurrent.futures import ThreadPoolExecutor

    from minio_tpu.object.erasure_object import ErasureSet
    from minio_tpu.ops.rs_device import DeviceBackend, mesh_info
    from minio_tpu.storage.local import LocalStorage

    print(f"SCALING_DEVICES={mesh_info()['mesh_devices']}")
    rng = np.random.default_rng(8)
    body = rng.integers(0, 256, size=1 << 20, dtype=np.uint8).tobytes()
    threads, per_thread = 16, (2 if _SMALL else 4)
    root = tempfile.mkdtemp(prefix="bench-scale-")
    try:
        disks = [LocalStorage(f"{root}/d{i}") for i in range(12)]
        for d in disks:
            d.make_vol("bench")
        es = ErasureSet(disks, parity=M, backend=DeviceBackend("auto"))
        ex = ThreadPoolExecutor(max_workers=threads)

        def worker(t):
            for i in range(per_thread):
                es.put_object("bench", f"o-{t}-{i}", body)

        list(ex.map(worker, range(threads)))      # warm + compile pass
        best = 0.0
        for _rep in range(1 if _SMALL else 2):
            t0 = time.perf_counter()
            list(ex.map(worker, range(threads)))
            wall = time.perf_counter() - t0
            best = max(best, threads * per_thread * len(body) / wall
                       / (1 << 30))
        ex.shutdown(wait=False)
        es.close()
        print(f"SCALING_GIBPS={best:.4f}")
    finally:
        shutil.rmtree(root, ignore_errors=True)


def _get_scaling() -> None:
    """Chip-count scaling of the batched device GET route: the 16-way
    concurrent 1 MiB GET aggregate with the decode routes PINNED to
    the device (MTPU_BATCH_FORCE=get=device,reconstruct=device)
    measured at 1/2/4/8 visible devices — the read-side mirror of
    put_scaling, same clean-subprocess harness (the device count must
    be fixed before JAX initializes; TPU hosts cap the mesh via
    MTPU_MESH_DEVICES over real chips, CPU containers get virtual host
    devices — plumbing proof, not speedup). Hot 1-block GETs only ride
    the device when coalesced, so the 16-way concurrency IS the
    measured cross-request batching. vs_baseline = max-devices over
    1-device aggregate; recorded in MULTICHIP_r07+."""
    import subprocess
    import sys as _sys
    sweep: dict[str, float] = {}
    devices: dict[str, int] = {}
    dropped: list[str] = []
    for n in (1, 2, 4, 8):
        env = {**_os.environ, "MTPU_SCALING_N": str(n),
               "MTPU_BATCH_FORCE": "get=device,reconstruct=device"}
        try:
            out = subprocess.run(
                [_sys.executable, __file__, "--get-scaling-probe"],
                capture_output=True, timeout=900, env=env)
            for line in out.stdout.decode().splitlines():
                if line.startswith("SCALING_GET_GIBPS="):
                    sweep[str(n)] = float(line.split("=", 1)[1])
                elif line.startswith("SCALING_DEVICES="):
                    devices[str(n)] = int(line.split("=", 1)[1])
        except Exception:  # noqa: BLE001 - sweep point best-effort
            pass
        if str(n) not in sweep:
            dropped.append(str(n))
    if not sweep:
        print(json.dumps({"metric": "get_scaling_aggregate_gibps",
                          "value": None, "unit": "GiB/s",
                          "error": "no sweep point completed"}))
        return
    ns = sorted(sweep, key=int)
    base, top = sweep[ns[0]], sweep[ns[-1]]
    print(json.dumps({
        "metric": "get_scaling_aggregate_gibps",
        "value": round(top, 3),
        "unit": "GiB/s",
        "vs_baseline": round(top / max(base, 1e-9), 3),
        "baseline_devices": int(ns[0]),
        "sweep_gibps": {k: round(sweep[k], 3) for k in ns},
        "dropped_points": dropped,
        "mesh_devices": devices,
        "route": "device_forced",
        "concurrency": 16,
    }))


def _get_scaling_probe() -> None:
    """Subprocess body for one get_scaling sweep point: pin the mesh
    width BEFORE JAX initializes, pre-put the working set, then
    measure the object-layer 16-way GET aggregate with the decode
    routes forced to the device."""
    import os
    import shutil
    import tempfile
    n = max(1, int(os.environ.get("MTPU_SCALING_N", "1") or 1))
    os.environ["MTPU_MESH_DEVICES"] = str(n)
    os.environ.setdefault("MTPU_BATCH_FORCE",
                          "get=device,reconstruct=device")
    if "cpu" in os.environ.get("JAX_PLATFORMS", "").lower():
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={n}").strip()
    from concurrent.futures import ThreadPoolExecutor

    from minio_tpu.object.erasure_object import ErasureSet
    from minio_tpu.ops.rs_device import DeviceBackend, mesh_info
    from minio_tpu.storage.local import LocalStorage

    print(f"SCALING_DEVICES={mesh_info()['mesh_devices']}")
    rng = np.random.default_rng(12)
    body = rng.integers(0, 256, size=1 << 20, dtype=np.uint8).tobytes()
    threads, per_thread = 16, (2 if _SMALL else 4)
    root = tempfile.mkdtemp(prefix="bench-getscale-")
    try:
        disks = [LocalStorage(f"{root}/d{i}") for i in range(12)]
        for d in disks:
            d.make_vol("bench")
        es = ErasureSet(disks, parity=M, backend=DeviceBackend("auto"))
        for t in range(threads):
            for i in range(per_thread):
                es.put_object("bench", f"o-{t}-{i}", body)
        ex = ThreadPoolExecutor(max_workers=threads)

        def worker(t):
            for i in range(per_thread):
                _, got = es.get_object("bench", f"o-{t}-{i}")
                assert len(got) == len(body)

        list(ex.map(worker, range(threads)))      # warm + compile pass
        best = 0.0
        for _rep in range(1 if _SMALL else 2):
            t0 = time.perf_counter()
            list(ex.map(worker, range(threads)))
            wall = time.perf_counter() - t0
            best = max(best, threads * per_thread * len(body) / wall
                       / (1 << 30))
        ex.shutdown(wait=False)
        es.close()
        print(f"SCALING_GET_GIBPS={best:.4f}")
    finally:
        shutil.rmtree(root, ignore_errors=True)


def _meta_listing() -> None:
    """Metadata plane at high cardinality: LIST/HEAD scenarios over a
    fabricated namespace (scripts/namespace_gen.py — direct-to-drive
    xl.meta journals, mixed kv/deep/flat/versioned profile).

    Scenarios (p50/p99 ms each):
      list_cold    first page of a kv/<aa>/ prefix right after a
                   metacache bump (fresh drive walk — the per-key
                   decode hot loop)
      list_root_cold  first page of the whole bucket (walks into the
                   flat-dir pathology)
      list_warm    the same page again while the walk stream is alive
      deep_page    first page under a 6-deep prefix chain
      head_storm   get_object_info over K distinct keys, two passes —
                   cold fan-out vs repeat (cache-class) behavior at a
                   cardinality far past the data-cache entry cap
      versioned    include_versions first page over the churn prefix
      persist_warm first page via a FRESH set over the same drives
                   after a completed walk persisted (restart warm
                   start / segment seek)

    Environment:
      MTPU_META_NS_ROOT     reuse an existing generated namespace
      MTPU_META_NS_OBJECTS  namespace size (default 10M; SMALL: 20k)
      MTPU_META_NS_DRIVES   drive count (default 1 at 10M — a 10M
                            namespace is inode-bound; SMALL: 4)
    Emits two metric lines (gated by scripts/bench_smoke.sh):
    meta_listing_list_cold_p50_ms and meta_listing_head_p50_ms; on
    hosts where the fixture cannot build, both carry value null and
    the smoke gate skips cleanly.
    """
    import shutil
    import tempfile

    sys_path_root = _os.path.dirname(_os.path.abspath(__file__))
    import sys as _sys
    if sys_path_root not in _sys.path:
        _sys.path.insert(0, sys_path_root)
    from scripts.namespace_gen import attach, generate, key_at

    # Wide persisted-walk warm-start window for the persist_warm
    # scenario (the default 2 s cross-restart contract would expire
    # between reps). Patched on the MODULE, not via the env knob: in a
    # multi-section bench run an earlier section already imported
    # metacache, which binds its TTL at import time.
    from minio_tpu.object import metacache as _mc_mod
    saved_ttl = _mc_mod._PERSIST_TTL
    _mc_mod._PERSIST_TTL = max(saved_ttl, 600.0)

    objects = int(_os.environ.get("MTPU_META_NS_OBJECTS", 0) or
                  (20_000 if _SMALL else 10_000_000))
    drives = int(_os.environ.get("MTPU_META_NS_DRIVES", 0) or
                 (4 if _SMALL else 1))
    root = _os.environ.get("MTPU_META_NS_ROOT", "")
    built_here = False

    def emit_skip(reason: str) -> None:
        # Explicit nulls for every gated column: scripts/bench_smoke.sh
        # skips a gate on an explicit null, hard-fails on a missing one.
        for m in ("meta_listing_list_cold_p50_ms",
                  "meta_listing_head_p50_ms"):
            print(json.dumps({"metric": m, "value": None,
                              "cold_p50_ms": None, "unit": "ms",
                              "skipped": reason}))

    if not root:
        # The fixture lives on /dev/shm or not at all: it needs ~6 KB
        # of tmpfs per object per drive, and syscall-cost on overlay
        # /tmp mounts is so high that a disk-built namespace measures
        # the mount, not the metadata plane. Tiny hosts skip cleanly
        # (the smoke gate treats the null value as "not measurable
        # here").
        try:
            st = _os.statvfs("/dev/shm")
            free = st.f_bavail * st.f_frsize
        except OSError:
            free = 0
        if free < objects * drives * 6144 + (1 << 30):
            emit_skip(f"namespace of {objects} objects x {drives} "
                      "drives does not fit this host's /dev/shm")
            return
        root = tempfile.mkdtemp(prefix="bench-ns-", dir="/dev/shm")
        built_here = True
        try:
            generate(root, objects, drives=drives)
        except Exception as e:  # noqa: BLE001 - fixture is best-effort
            shutil.rmtree(root, ignore_errors=True)
            emit_skip(f"namespace build failed: {e}")
            return

    def pct(ts, p):
        ts = sorted(ts)
        return round(ts[min(len(ts) - 1, len(ts) * p // 100)] * 1e3, 2)

    scen: dict = {}
    es = attach(root, drives)
    try:
        bucket = "ns"
        reps = 5 if _SMALL else 12
        # Prefixes with real population under the mixed profile: kv
        # second hex digit cycles fastest with index.
        kv_prefixes = [f"kv/{h}{h2}/" for h in "0123456789abcdef"
                       for h2 in "0369cf"]

        def cold_pages(prefixes, n, **kw):
            lat = []
            for p in prefixes[:n]:
                es.metacache.bump(bucket)
                t0 = time.perf_counter()
                page = es.list_objects(bucket, prefix=p, max_keys=1000,
                                       **kw)
                lat.append(time.perf_counter() - t0)
                assert page.objects or page.prefixes, p
            return lat

        lat = cold_pages(kv_prefixes, reps)
        scen["list_cold"] = {"p50_ms": pct(lat, 50), "p99_ms": pct(lat, 99)}

        # Whole-bucket first page (walks into the flat/ pathology).
        lat = []
        for _ in range(max(3, reps // 3)):
            es.metacache.bump(bucket)
            t0 = time.perf_counter()
            page = es.list_objects(bucket, max_keys=1000)
            lat.append(time.perf_counter() - t0)
            assert page.objects
        scen["list_root_cold"] = {"p50_ms": pct(lat, 50),
                                  "p99_ms": pct(lat, 99)}

        # Warm: same prefix, walk stream alive.
        es.metacache.bump(bucket)
        es.list_objects(bucket, prefix=kv_prefixes[0], max_keys=1000)
        lat = []
        for _ in range(reps):
            t0 = time.perf_counter()
            es.list_objects(bucket, prefix=kv_prefixes[0], max_keys=1000)
            lat.append(time.perf_counter() - t0)
        scen["list_warm"] = {"p50_ms": pct(lat, 50), "p99_ms": pct(lat, 99)}

        # Deep-prefix page (a = (i>>8)&7, b = (i>>12)&7: these combos
        # are populated from a few thousand objects up).
        deep_prefixes = [f"deep/{a}/{b}/" for b in "012"
                         for a in "02461357"]
        lat = cold_pages(deep_prefixes, max(3, reps // 2))
        scen["deep_page"] = {"p50_ms": pct(lat, 50), "p99_ms": pct(lat, 99)}

        # Delimiter browse one level under kv/ (the S3-console shape):
        # the shallow walk answers from O(page) probes; a deep walk
        # must stream the whole subtree into the collapse. One rep at
        # full scale — the pre-optimization cost is the finding.
        lat = []
        for _ in range(1 if objects > 1_000_000 else max(2, reps // 3)):
            es.metacache.bump(bucket)
            t0 = time.perf_counter()
            page = es.list_objects(bucket, prefix="kv/", delimiter="/",
                                   max_keys=1000)
            lat.append(time.perf_counter() - t0)
            assert page.prefixes
        scen["browse_delim"] = {"p50_ms": pct(lat, 50),
                                "p99_ms": pct(lat, 99),
                                "prefixes": len(page.prefixes),
                                "truncated": page.is_truncated}

        # Versioned listing over the churn prefix.
        lat = cold_pages(["ver/"], 1, include_versions=True)
        for _ in range(max(2, reps // 2) - 1):
            lat += cold_pages(["ver/"], 1, include_versions=True)
        scen["versioned"] = {"p50_ms": pct(lat, 50), "p99_ms": pct(lat, 99)}

        # HEAD storm: cardinality far past the data-class cache cap.
        es.metacache.bump(bucket)        # cancel walks, flush caches
        nkeys = min(2000 if _SMALL else 20_000, max(objects // 4, 100))
        stride = max(1, objects // nkeys)
        keys = [key_at(i * stride, objects) for i in range(nkeys)
                if i * stride < objects]
        cold_lat, hot_lat = [], []
        for k in keys:
            t0 = time.perf_counter()
            es.get_object_info(bucket, k)
            cold_lat.append(time.perf_counter() - t0)
        for k in keys:
            t0 = time.perf_counter()
            es.get_object_info(bucket, k)
            hot_lat.append(time.perf_counter() - t0)
        scen["head_storm"] = {
            "keys": len(keys),
            "cold_p50_ms": pct(cold_lat, 50), "cold_p99_ms": pct(cold_lat, 99),
            "hot_p50_ms": pct(hot_lat, 50), "hot_p99_ms": pct(hot_lat, 99)}

        # Persisted warm start: complete a small prefix walk, let it
        # persist, then a FRESH set over the same drives pages it.
        warm_prefix = "kv/00/"
        es.metacache.bump(bucket)
        marker = ""
        while True:
            page = es.list_objects(bucket, prefix=warm_prefix,
                                   marker=marker, max_keys=1000)
            if not page.is_truncated:
                break
            marker = page.next_marker
        time.sleep(0.3)        # persist runs before done; small safety
        lat = []
        for _ in range(max(3, reps // 2)):
            es2 = attach(root, drives)
            t0 = time.perf_counter()
            page = es2.list_objects(bucket, prefix=warm_prefix,
                                    max_keys=1000)
            lat.append(time.perf_counter() - t0)
            assert page.objects
            es2.close()
        scen["persist_warm"] = {"p50_ms": pct(lat, 50),
                                "p99_ms": pct(lat, 99)}
    finally:
        es.close()
        _mc_mod._PERSIST_TTL = saved_ttl
        if built_here and _os.environ.get("MTPU_META_NS_KEEP", "") != "1":
            shutil.rmtree(root, ignore_errors=True)

    common = {"unit": "ms", "vs_baseline": None, "objects": objects,
              "drives": drives}
    print(json.dumps({
        "metric": "meta_listing_list_cold_p50_ms",
        "value": scen["list_cold"]["p50_ms"],
        **common, "scenarios": scen,
    }))
    print(json.dumps({
        "metric": "meta_listing_head_p50_ms",
        "value": scen["head_storm"]["hot_p50_ms"],
        "cold_p50_ms": scen["head_storm"]["cold_p50_ms"],
        **common,
    }))


# One probe subprocess can serve several sections (PUT + GET
# aggregates): cache its parsed output for the process lifetime.
_PROBE_LINES: dict | None = None


def _served_probe_value(key: str):
    """Value of `key` from the front-end probe subprocess (run once)."""
    global _PROBE_LINES
    import subprocess
    import sys as _sys
    if _PROBE_LINES is None:
        _PROBE_LINES = {}
        try:
            out = subprocess.run(
                [_sys.executable, __file__, "--serve-probe"],
                capture_output=True, timeout=900,
                env={**_os.environ, "JAX_PLATFORMS": "cpu"})
            for line in out.stdout.decode().splitlines():
                if "=" in line and line.split("=", 1)[0].isupper():
                    try:
                        v = float(line.split("=", 1)[1])
                    except ValueError:
                        continue
                    if v == v:                  # NaN-guard
                        _PROBE_LINES[line.split("=", 1)[0]] = v
        except Exception:  # noqa: BLE001 - front-end probe best-effort
            pass
    return _PROBE_LINES.get(key)


def _serve_probe() -> None:
    """Subprocess body for the front-end aggregate: boot the pre-forked
    worker fleet on local drives, drive 16 concurrent signed HTTP PUT
    clients, print SERVED_GIBPS=<value>."""
    import hashlib
    import http.client
    import os
    import shutil
    import signal
    import subprocess
    import sys as _sys
    import tempfile
    from concurrent.futures import ThreadPoolExecutor

    root = tempfile.mkdtemp(prefix="bench-serve-")
    port = 19750 + (os.getpid() % 200)
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", MTPU_HTTP_WORKERS=str(
        max(2, os.cpu_count() or 2)))
    srv = subprocess.Popen(
        [_sys.executable, "-m", "minio_tpu.server",
         "--address", f"127.0.0.1:{port}", "--scanner-interval", "0",
         f"{root}/d{{1...12}}"],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        cwd=os.path.dirname(os.path.abspath(__file__)))
    try:
        _sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from tests.s3client import S3Client
        deadline = time.time() + 90
        while time.time() < deadline:
            try:
                if S3Client(f"127.0.0.1:{port}").request(
                        "GET", "/minio/health/live", sign=False)[0] == 200:
                    break
            except OSError:
                time.sleep(0.5)
        else:
            return          # never ready: parent records served=None
        threads, per_thread = (16, 3) if _SMALL else (16, 6)
        body = np.random.default_rng(3).integers(
            0, 256, size=1 << 20, dtype=np.uint8).tobytes()
        cli0 = S3Client(f"127.0.0.1:{port}")
        assert cli0.request("PUT", "/bench")[0] == 200

        # Persistent connections (the SDK connection-pool shape): each
        # client thread keeps ONE connection hot across its requests,
        # riding the serve loop's keep-alive fast path — a cold
        # handshake per request would measure TCP setup, not serving.
        clients = [S3Client(f"127.0.0.1:{port}", keepalive=True)
                   for _ in range(threads)]

        def worker(tag, t):
            cli = clients[t]
            for i in range(per_thread):
                st, _, _ = cli.request("PUT", f"/bench/{tag}-{t}-{i}",
                                       body=body)
                assert st == 200, st

        ex = ThreadPoolExecutor(max_workers=threads)
        list(ex.map(lambda t: worker("w", t), range(threads)))  # warm
        # Best-of-2 measured passes, mirroring the object-layer
        # sections: aggregate numbers on a shared box are scheduler-
        # noise-prone and the served/object RATIO is gated, so both
        # sides of it deserve the same noise floor treatment.
        wall = None
        for _rep in range(2):
            t0 = time.perf_counter()
            list(ex.map(lambda t: worker("m", t), range(threads)))
            dt = time.perf_counter() - t0
            wall = dt if wall is None else min(wall, dt)
        print("SERVED_GIBPS="
              f"{threads * per_thread * len(body) / wall / (1 << 30):.4f}")

        # Small-object storm through the front end: 4 KiB signed PUTs
        # on the same keep-alive clients — the served column of the
        # small_put section (group-commit lanes engaged inside each
        # worker under concurrency).
        small = np.random.default_rng(8).integers(
            0, 256, size=4096, dtype=np.uint8).tobytes()

        def small_worker(tag, t):
            cli = clients[t]
            for i in range(per_small):
                st, _, _ = cli.request("PUT", f"/bench/sp-{tag}-{t}-{i}",
                                       body=small)
                assert st == 200, st

        per_small = 12 if _SMALL else 40
        list(ex.map(lambda t: small_worker("w", t), range(threads)))
        wall = None
        for _rep in range(2):
            t0 = time.perf_counter()
            list(ex.map(lambda t: small_worker(f"m{_rep}", t),
                        range(threads)))
            dt = time.perf_counter() - t0
            wall = dt if wall is None else min(wall, dt)
        print("SERVED_SMALL_PUT_OPS="
              f"{threads * per_small / wall:.2f}")

        # One reusable receive buffer per client thread: the GET probe
        # reads bodies via recv_into (S3Client.get_into), so the
        # CLIENT costs per request are one small signed head + raw
        # socket receives — the measured number is the server, not
        # http.client object churn on the same cores.
        bufs = [bytearray(len(body)) for _ in range(threads)]

        def getter(tag, t):
            cli = clients[t]
            for i in range(per_thread):
                st, n = cli.get_into(f"/bench/{tag}-{t}-{i}", bufs[t])
                assert st == 200 and n == len(body), st

        # Served GET aggregate over the objects the measured pass wrote
        # (warm pass primes caches — repeat reads are the steady state).
        list(ex.map(lambda t: getter("m", t), range(threads)))  # warm
        wall = None
        for _rep in range(2):
            t0 = time.perf_counter()
            list(ex.map(lambda t: getter("m", t), range(threads)))
            dt = time.perf_counter() - t0
            wall = dt if wall is None else min(wall, dt)
        print("SERVED_GET_GIBPS="
              f"{threads * per_thread * len(body) / wall / (1 << 30):.4f}")
    finally:
        srv.send_signal(signal.SIGTERM)
        try:
            srv.wait(timeout=20)
        except subprocess.TimeoutExpired:
            srv.kill()
        shutil.rmtree(root, ignore_errors=True)


def _connections() -> None:
    """Connection-plane bench (ROADMAP item 6): what an IDLE keep-alive
    connection costs, and whether the served GET aggregate survives
    client fan-in.

      idle rss        N idle keep-alive connections held against a
                      2-worker fleet; the fleet's RSS delta over its
                      quiescent baseline, per connection. Under the
                      epoll loop an idle connection is a parked fd with
                      a hibernated recv buffer; under the thread path
                      (MTPU_HTTP_EVENTLOOP=off, measured back-to-back
                      as the pre-PR column) it pins a thread stack.
      get ramp        served GET aggregate (1 MiB object) as the
                      client connection count ramps — the measurement
                      r10 could not make with one hot socket
                      (tests/s3client.py ramp_get: one persistent raw
                      socket per client thread).

    Emits explicit-null lines on fd-limited hosts (RLIMIT_NOFILE too
    small for the connection target) so the smoke gate skips cleanly.

    Environment:
      MTPU_BENCH_IDLE_CONNS   idle-connection target (default 10000,
                              2000 under MTPU_BENCH_SMALL)
    """
    try:
        _connections_inner()
    except Exception as e:  # noqa: BLE001 - boot/socket failure
        for m in ("connections_idle_rss_per_conn_kib",
                  "connections_get_ramp_gibps"):
            print(json.dumps({"metric": m, "value": None,
                              "skip": f"{type(e).__name__}: {e}"}))


def _conn_tree_rss_kib(pid: int) -> int:
    """VmRSS sum (KiB) of `pid` and every descendant (the pre-forked
    fleet: parent + workers)."""
    def descend(p: int) -> list:
        out = [p]
        try:
            with open(f"/proc/{p}/task/{p}/children") as f:
                kids = f.read().split()
        except OSError:
            kids = []
        for k in kids:
            out += descend(int(k))
        return out

    total = 0
    for p in descend(pid):
        try:
            with open(f"/proc/{p}/status") as f:
                for line in f:
                    if line.startswith("VmRSS:"):
                        total += int(line.split()[1])
                        break
        except OSError:
            pass
    return total


def _connections_inner() -> None:
    import shutil
    import signal
    import socket
    import subprocess
    import sys as _sys
    import tempfile
    from concurrent.futures import ThreadPoolExecutor

    repo = _os.path.dirname(_os.path.abspath(__file__))
    if repo not in _sys.path:
        _sys.path.insert(0, repo)
    from tests.s3client import S3Client, ramp_get

    n_idle = int(_os.environ.get("MTPU_BENCH_IDLE_CONNS", 0) or
                 (2000 if _SMALL else 10000))
    ramp = (1, 4, 16) if _SMALL else (1, 4, 16, 64, 256)
    ramp_secs = 1.5 if _SMALL else 3.0

    # fd budget: this process holds every idle client socket; the
    # server process holds the matching accepted fds (its own limit is
    # inherited from ours). Raise soft to hard, then gate.
    import resource
    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    want_fds = n_idle + 1024
    if soft < want_fds and hard >= want_fds:
        resource.setrlimit(resource.RLIMIT_NOFILE, (want_fds, hard))
        soft = want_fds
    if soft < want_fds:
        for m in ("connections_idle_rss_per_conn_kib",
                  "connections_get_ramp_gibps"):
            print(json.dumps({
                "metric": m, "value": None,
                "skip": f"RLIMIT_NOFILE {soft} < {want_fds} "
                        f"needed for {n_idle} idle connections"}))
        return

    def boot(root: str, eventloop: bool):
        port = 19350 + (_os.getpid() % 200) + (0 if eventloop else 1)
        env = dict(_os.environ)
        env.update(JAX_PLATFORMS="cpu", MTPU_HTTP_WORKERS="2",
                   # The idle probe must outlive its own setup window:
                   # a reaped connection would under-count RSS.
                   MTPU_HTTP_KEEPALIVE_S="600")
        if eventloop:
            env.pop("MTPU_HTTP_EVENTLOOP", None)
        else:
            env["MTPU_HTTP_EVENTLOOP"] = "off"
        proc = subprocess.Popen(
            [_sys.executable, "-m", "minio_tpu.server",
             "--address", f"127.0.0.1:{port}", "--scanner-interval", "0",
             f"{root}/d{{1...4}}"],
            env=env, cwd=repo,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        addr = f"127.0.0.1:{port}"
        deadline = time.time() + 90
        while time.time() < deadline:
            if proc.poll() is not None:
                raise RuntimeError("fleet died during boot")
            try:
                if S3Client(addr).request(
                        "GET", "/minio/health/live", sign=False)[0] == 200:
                    return proc, addr
            except OSError:
                time.sleep(0.4)
        proc.kill()
        raise RuntimeError("fleet failed to boot in 90s")

    def shutdown(proc) -> None:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=25)
        except subprocess.TimeoutExpired:
            proc.kill()

    def idle_probe(proc, addr) -> dict:
        """Open n_idle keep-alive connections (one served request each,
        then parked idle) and charge the fleet's RSS delta to them."""
        host, _, port = addr.rpartition(":")
        req = (f"GET /minio/health/live HTTP/1.1\r\nHost: {addr}\r\n"
               "\r\n").encode()
        socks: list = [None] * n_idle
        failures = [0]

        def opener(lo: int, hi: int) -> None:
            for i in range(lo, hi):
                try:
                    s = socket.create_connection((host, int(port)),
                                                 timeout=30)
                    s.sendall(req)
                    buf = b""
                    while b"\r\n\r\n" not in buf:
                        got = s.recv(4096)
                        if not got:
                            raise ConnectionError("EOF in idle prime")
                        buf += got
                    head, rest = buf.split(b"\r\n\r\n", 1)
                    clen = 0
                    for line in head.split(b"\r\n")[1:]:
                        if line[:15].lower() == b"content-length:":
                            clen = int(line[15:])
                    while len(rest) < clen:
                        rest += s.recv(4096)
                    socks[i] = s
                except OSError:
                    failures[0] += 1
        time.sleep(2)
        rss0 = _conn_tree_rss_kib(proc.pid)
        step = max(1, n_idle // 32)
        with ThreadPoolExecutor(max_workers=32) as ex:
            list(ex.map(lambda lo: opener(lo, min(lo + step, n_idle)),
                        range(0, n_idle, step)))
        held = sum(1 for s in socks if s is not None)
        time.sleep(3)              # let buffers hibernate / settle
        rss1 = _conn_tree_rss_kib(proc.pid)
        for s in socks:
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
        return {"conns_held": held, "failures": failures[0],
                "rss_base_mib": round(rss0 / 1024, 1),
                "rss_idle_mib": round(rss1 / 1024, 1),
                "kib_per_conn": round((rss1 - rss0) / max(held, 1), 2)}

    def ramp_probe(addr) -> list:
        cli = S3Client(addr)
        assert cli.request("PUT", "/connb")[0] == 200
        body = np.random.default_rng(5).integers(
            0, 256, size=1 << 20, dtype=np.uint8).tobytes()
        assert cli.request("PUT", "/connb/ramp", body=body)[0] == 200
        out = []
        for conns in ramp:
            r = ramp_get(addr, "/connb/ramp", len(body), conns,
                         duration_s=ramp_secs)
            out.append(r)
        return out

    results: dict = {}
    for front in ("eventloop", "threads"):
        root = tempfile.mkdtemp(prefix=f"bench-conn-{front}-")
        try:
            proc, addr = boot(root, eventloop=(front == "eventloop"))
            try:
                idle = idle_probe(proc, addr)
                ramps = ramp_probe(addr)
            finally:
                shutdown(proc)
            results[front] = {"idle": idle, "ramp": ramps}
        except Exception as e:  # noqa: BLE001 - the thread path may
            # genuinely fail to hold the target (10k OS threads); an
            # explicit error column is the honest pre-PR record.
            results[front] = {"error": f"{type(e).__name__}: {e}"}
        finally:
            shutil.rmtree(root, ignore_errors=True)

    loop = results.get("eventloop", {})
    pre = results.get("threads", {})
    if "idle" not in loop:
        for m in ("connections_idle_rss_per_conn_kib",
                  "connections_get_ramp_gibps"):
            print(json.dumps({"metric": m, "value": None,
                              "skip": loop.get("error", "probe failed")}))
        return

    idle = loop["idle"]
    print(json.dumps({
        "metric": "connections_idle_rss_per_conn_kib",
        "value": idle["kib_per_conn"],
        "unit": "KiB/conn",
        "conns": idle["conns_held"],
        "open_failures": idle["failures"],
        "rss_base_mib": idle["rss_base_mib"],
        "rss_idle_mib": idle["rss_idle_mib"],
        "pre_pr_threadpath": pre.get("idle")
        or {"error": pre.get("error", "probe failed")},
        "workers": 2,
    }))
    ramps = loop["ramp"]
    tail = ramps[-1]
    print(json.dumps({
        "metric": "connections_get_ramp_gibps",
        "value": tail["agg_gibps"],
        "unit": "GiB/s",
        "connections": tail["connections"],
        "ramp": ramps,
        "vs_c1": round(tail["agg_gibps"]
                       / max(ramps[0]["agg_gibps"], 1e-9), 3),
        "pre_pr_threadpath": pre.get("ramp")
        or {"error": pre.get("error", "probe failed")},
        "workers": 2,
    }))


def _hot_get() -> None:
    """Hot read tier (ROADMAP item 4): served GET aggregate of the
    frequency-admitted RAM cache under a zipfian fan-in ramp, against
    the erasure read path like-for-like in ONE bench run.

    Two back-to-back 2-worker fleets on the same host serve the SAME
    object set (1 MiB bodies) under the SAME zipfian ramp (rank
    frequency ∝ 1/(i+1)^alpha — the skew the tinyLFU admission is
    built for): the first with the hot cache on (a warmup pass pins
    the set, so the measured window is the RAM hit path — loop
    short-circuit plus handler hits), the second with MTPU_HOT_CACHE=off
    (every GET pays the erasure fan-out: the kill-switch column IS the
    erasure column). The on-fleet's metrics scrape must show
    response_path{path=hotcache} > 0 or the run is reported as failed —
    a silently-disengaged cache must not report a throughput win.

    Emits explicit-null lines on fd-limited hosts (RLIMIT_NOFILE below
    the connection target) so the smoke gate skips cleanly.
    """
    try:
        _hot_get_inner()
    except Exception as e:  # noqa: BLE001 - boot/socket failure
        print(json.dumps({"metric": "hot_get_gibps", "value": None,
                          "skip": f"{type(e).__name__}: {e}"}))


def _hot_get_inner() -> None:
    import shutil
    import signal
    import subprocess
    import sys as _sys
    import tempfile

    repo = _os.path.dirname(_os.path.abspath(__file__))
    if repo not in _sys.path:
        _sys.path.insert(0, repo)
    from tests.s3client import S3Client, ramp_get

    ramp = (16, 64) if _SMALL else (16, 64, 256)
    ramp_secs = 1.5 if _SMALL else 3.0
    n_objects = 16 if _SMALL else 32
    alpha = 1.0
    body = np.random.default_rng(7).integers(
        0, 256, size=1 << 20, dtype=np.uint8).tobytes()

    import resource
    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    want_fds = max(ramp) * 2 + 512
    if soft < want_fds and hard >= want_fds:
        resource.setrlimit(resource.RLIMIT_NOFILE, (want_fds, hard))
        soft = want_fds
    if soft < want_fds:
        print(json.dumps({
            "metric": "hot_get_gibps", "value": None,
            "skip": f"RLIMIT_NOFILE {soft} < {want_fds} needed for "
                    f"{max(ramp)} ramp connections"}))
        return

    def boot(root: str, hot_on: bool):
        port = 19560 + (_os.getpid() % 200) + (0 if hot_on else 1)
        env = dict(_os.environ)
        env.update(JAX_PLATFORMS="cpu", MTPU_HTTP_WORKERS="2")
        if hot_on:
            env.pop("MTPU_HOT_CACHE", None)
        else:
            env["MTPU_HOT_CACHE"] = "off"
        proc = subprocess.Popen(
            [_sys.executable, "-m", "minio_tpu.server",
             "--address", f"127.0.0.1:{port}", "--scanner-interval", "0",
             f"{root}/d{{1...4}}"],
            env=env, cwd=repo,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        addr = f"127.0.0.1:{port}"
        deadline = time.time() + 90
        while time.time() < deadline:
            if proc.poll() is not None:
                raise RuntimeError("fleet died during boot")
            try:
                if S3Client(addr).request(
                        "GET", "/minio/health/live", sign=False)[0] == 200:
                    return proc, addr
            except OSError:
                time.sleep(0.4)
        proc.kill()
        raise RuntimeError("fleet failed to boot in 90s")

    def shutdown(proc) -> None:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=25)
        except subprocess.TimeoutExpired:
            proc.kill()

    def measure(addr: str, hot_on: bool):
        cli = S3Client(addr)
        assert cli.request("PUT", "/hotb")[0] == 200
        paths = []
        for i in range(n_objects):
            p = f"/hotb/o{i:03d}"
            assert cli.request("PUT", p, body=body)[0] == 200
            paths.append(p)
        # Warmup: two passes on fresh connections so BOTH workers'
        # caches admit the set before the measured window.
        for _ in range(2):
            for p in paths:
                st, _, got = S3Client(addr).request("GET", p)
                assert st == 200 and len(got) == len(body)
        ramps = []
        for conns in ramp:
            ramps.append(ramp_get(addr, paths[0], len(body), conns,
                                  duration_s=ramp_secs, paths=paths,
                                  alpha=alpha))
        hot_total = 0
        st, _, text = cli.request("GET", "/minio/v2/metrics/cluster")
        assert st == 200
        needle = 'minio_tpu_http_response_path_total{path="hotcache"}'
        for line in text.decode(errors="replace").splitlines():
            if line.startswith(needle):
                hot_total = int(float(line.rsplit(" ", 1)[1]))
        if hot_on and hot_total <= 0:
            raise RuntimeError("hot cache never engaged during the "
                               "measured window (hotcache path total 0)")
        if not hot_on and hot_total > 0:
            raise RuntimeError("kill switch leaked: hotcache path total "
                               f"{hot_total} with MTPU_HOT_CACHE=off")
        return ramps, hot_total

    results: dict = {}
    for mode in ("hot", "erasure"):
        root = tempfile.mkdtemp(prefix=f"bench-hotget-{mode}-")
        try:
            proc, addr = boot(root, hot_on=(mode == "hot"))
            try:
                ramps, hot_total = measure(addr, hot_on=(mode == "hot"))
            finally:
                shutdown(proc)
            results[mode] = {"ramp": ramps, "hot_path_total": hot_total}
        except Exception as e:  # noqa: BLE001 - explicit error column
            results[mode] = {"error": f"{type(e).__name__}: {e}"}
        finally:
            shutil.rmtree(root, ignore_errors=True)

    hot_r = results.get("hot", {})
    era_r = results.get("erasure", {})
    if "ramp" not in hot_r:
        print(json.dumps({"metric": "hot_get_gibps", "value": None,
                          "skip": hot_r.get("error", "probe failed")}))
        return
    tail = hot_r["ramp"][-1]
    era_tail = era_r["ramp"][-1] if "ramp" in era_r else None
    print(json.dumps({
        "metric": "hot_get_gibps",
        "value": tail["agg_gibps"],
        "unit": "GiB/s",
        "connections": tail["connections"],
        "objects": n_objects,
        "object_mib": 1,
        "alpha": alpha,
        "ramp": hot_r["ramp"],
        "hot_path_total": hot_r["hot_path_total"],
        "vs_erasure": (round(tail["agg_gibps"]
                             / max(era_tail["agg_gibps"], 1e-9), 2)
                       if era_tail else None),
        "erasure_hot_cache_off": era_r.get("ramp")
        or {"error": era_r.get("error", "probe failed")},
        "workers": 2,
    }))


def _distributed() -> None:
    """Distributed topology vs single node, through REAL spawned server
    processes (tests/cluster.py): an N-node in-container cluster (real
    grid mesh, dsync quorums, remote drives with the walk_scan stream)
    versus ONE process over the same drive count, same probes:

      put/get aggregate   concurrent 1 MiB PUT/GET round-robined over
                          every node's S3 port (GiB/s)
      listing page p50    first page of a bucket of small keys, with a
                          namespace mutation before each rep so every
                          measured page pays a REAL distributed walk —
                          the remote walk_scan trimmed-summary stream,
                          not a cached stream re-read

    Each metric also carries an in-run OLD-PLANE column: the same
    multi-node probe against a third cluster booted with
    MTPU_GRID_NATIVE=off (per-frame msgpack bulk bytes, no sendfile,
    no raw frames). Both columns share this run's scheduler weather,
    so vs_old_plane is the stable cross-run signal for the native
    plane on a loaded host — the raw aggregates measure the box.

    Emits explicit-null lines on hosts that cannot run the cluster
    (1 core, or boot failure) so the smoke gate skips cleanly.

    Environment:
      MTPU_CLUSTER_BENCH_NODES   cluster width (default 4)
    """
    try:
        _distributed_inner()
    except Exception as e:  # noqa: BLE001 - tiny host / boot failure
        for m in ("distributed_put_aggregate_gibps",
                  "distributed_get_aggregate_gibps",
                  "distributed_list_page_p50_ms"):
            print(json.dumps({"metric": m, "value": None,
                              "vs_old_plane": None,
                              "skip": f"{type(e).__name__}: {e}"}))


def _distributed_inner() -> None:
    import shutil
    import statistics
    import sys as _sys
    import tempfile
    from concurrent.futures import ThreadPoolExecutor

    # No core-count gate: the harness boots 4-8 node clusters on
    # 1-2 core containers (tests/test_cluster.py proves it); a host
    # that truly cannot boot the cluster fails wait_ready and lands in
    # the explicit-null skip path organically.
    repo = _os.path.dirname(_os.path.abspath(__file__))
    if repo not in _sys.path:
        _sys.path.insert(0, repo)
    from tests.cluster import Cluster
    from tests.s3client import S3Client

    nodes = int(_os.environ.get("MTPU_CLUSTER_BENCH_NODES", 0) or 4)
    drives_per_node = max(1, 8 // nodes)
    total_drives = nodes * drives_per_node
    threads, per_thread = (8, 2) if _SMALL else (16, 4)
    n_list_keys = 300 if _SMALL else 1000
    list_reps = 7 if _SMALL else 11
    rng = np.random.default_rng(7)
    body = rng.integers(0, 256, size=1 << 20, dtype=np.uint8).tobytes()

    def probe(cluster) -> dict:
        addrs = [cluster.address(i) for i in range(cluster.n)]

        def req(cli_box, addr, method, path, **kw):
            # Transient transport retry: N server processes contending
            # 1-2 cores occasionally reset a connection mid-burst; the
            # retry (fresh connection) keeps the aggregate honest —
            # its wall-clock cost stays inside the measured window.
            for attempt in range(4):
                try:
                    return cli_box[0].request(method, path, **kw)
                except OSError:
                    if attempt == 3:
                        raise
                    cli_box[0] = S3Client(addr)

        mk = [S3Client(addrs[0])]
        st, _, b = req(mk, addrs[0], "PUT", "/dbench")
        assert st == 200, b

        # Unmeasured warmup: one PUT+GET round-trip through EVERY
        # node primes grid connections, breakers, bufpools, and page
        # cache so the first measured column does not pay cluster
        # cold-start that the later columns skip (the probe runs
        # three clusters back-to-back; without this the first one
        # reads systematically slower regardless of plane).
        for wi, addr in enumerate(addrs):
            wcli = [S3Client(addr)]
            st, _, b = req(wcli, addr, "PUT", f"/dbench/warm-{wi}",
                           body=body)
            assert st == 200, b
            st, _, got = req(wcli, addr, "GET", f"/dbench/warm-{wi}")
            assert st == 200 and len(got) == len(body)

        def put_worker(t):
            addr = addrs[t % len(addrs)]
            cli = [S3Client(addr)]
            for i in range(per_thread):
                st, _, b = req(cli, addr, "PUT", f"/dbench/o-{t}-{i}",
                               body=body)
                assert st == 200, b

        def get_worker(t):
            addr = addrs[t % len(addrs)]
            cli = [S3Client(addr)]
            for i in range(per_thread):
                st, _, got = req(cli, addr, "GET", f"/dbench/o-{t}-{i}")
                assert st == 200 and len(got) == len(body)

        ex = ThreadPoolExecutor(max_workers=threads)
        t0 = time.perf_counter()
        list(ex.map(put_worker, range(threads)))
        put_wall = time.perf_counter() - t0
        t0 = time.perf_counter()
        list(ex.map(get_worker, range(threads)))
        get_wall = time.perf_counter() - t0
        ex.shutdown(wait=False)
        agg = threads * per_thread * len(body) / (1 << 30)

        # Listing fixture: small keys, then pages that each pay a
        # fresh distributed walk (a tiny PUT bumps the generation
        # between reps, orphaning the cached stream).
        small = b"x" * 4096
        def fill(t):
            addr = addrs[t % len(addrs)]
            cli = [S3Client(addr)]
            for i in range(t, n_list_keys, threads):
                st, _, b2 = req(cli, addr, "PUT", f"/dbench/k/{i:06d}",
                                body=small)
                assert st == 200, b2
        ex = ThreadPoolExecutor(max_workers=threads)
        list(ex.map(fill, range(threads)))
        ex.shutdown(wait=False)
        laddr = addrs[min(1, len(addrs) - 1)]
        lister = [S3Client(laddr)]
        lat = []
        for rep in range(list_reps):
            st, _, b2 = req(mk, addrs[0], "PUT", f"/dbench/bump-{rep}",
                            body=b"")
            assert st == 200, b2
            t0 = time.perf_counter()
            st, _, page = req(lister, laddr, "GET", "/dbench",
                              query={"prefix": "k/", "max-keys": "100"})
            lat.append((time.perf_counter() - t0) * 1000)
            assert st == 200 and page.count(b"<Key>") == 100, page[:300]
        lat.sort()
        return {"put_gibps": agg / put_wall, "get_gibps": agg / get_wall,
                "list_p50_ms": statistics.median(lat),
                "list_p99_ms": lat[min(len(lat) - 1,
                                       int(0.99 * len(lat)))]}

    root = tempfile.mkdtemp(prefix="bench-dist-")
    try:
        with Cluster(_os.path.join(root, "multi"), nodes=nodes,
                     drives_per_node=drives_per_node) as cluster:
            multi = probe(cluster)
        # In-run old-plane column: the SAME multi-node probe with the
        # native grid data plane killed (per-frame msgpack bulk bytes,
        # blocking chunked streams, no sendfile). Same host, same run,
        # same scheduler weather — the ratio is the gateable signal.
        with Cluster(_os.path.join(root, "old"), nodes=nodes,
                     drives_per_node=drives_per_node,
                     env={"MTPU_GRID_NATIVE": "off"}) as old_cluster:
            old = probe(old_cluster)
        with Cluster(_os.path.join(root, "single"), nodes=1,
                     drives_per_node=total_drives) as single_cluster:
            single = probe(single_cluster)
    finally:
        shutil.rmtree(root, ignore_errors=True)

    print(json.dumps({
        "metric": "distributed_put_aggregate_gibps",
        "value": round(multi["put_gibps"], 3),
        "unit": "GiB/s",
        "nodes": nodes, "drives": total_drives,
        "single_node_gibps": round(single["put_gibps"], 3),
        "vs_single_node": round(multi["put_gibps"]
                                / max(single["put_gibps"], 1e-9), 3),
        "old_plane_gibps": round(old["put_gibps"], 3),
        "vs_old_plane": round(multi["put_gibps"]
                              / max(old["put_gibps"], 1e-9), 3),
        "concurrency": threads,
    }))
    print(json.dumps({
        "metric": "distributed_get_aggregate_gibps",
        "value": round(multi["get_gibps"], 3),
        "unit": "GiB/s",
        "nodes": nodes, "drives": total_drives,
        "single_node_gibps": round(single["get_gibps"], 3),
        "vs_single_node": round(multi["get_gibps"]
                                / max(single["get_gibps"], 1e-9), 3),
        "old_plane_gibps": round(old["get_gibps"], 3),
        "vs_old_plane": round(multi["get_gibps"]
                              / max(old["get_gibps"], 1e-9), 3),
        "concurrency": threads,
    }))
    print(json.dumps({
        "metric": "distributed_list_page_p50_ms",
        "value": round(multi["list_p50_ms"], 2),
        "unit": "ms",
        "p99_ms": round(multi["list_p99_ms"], 2),
        "nodes": nodes, "drives": total_drives,
        "keys": n_list_keys,
        "single_node_p50_ms": round(single["list_p50_ms"], 2),
        "vs_single_node": round(multi["list_p50_ms"]
                                / max(single["list_p50_ms"], 1e-9), 3),
        "old_plane_p50_ms": round(old["list_p50_ms"], 2),
        "vs_old_plane": round(multi["list_p50_ms"]
                              / max(old["list_p50_ms"], 1e-9), 3),
    }))


def _cluster_get() -> None:
    """Inter-node shard-fetch throughput: the grid storage read plane
    in isolation (what a remote GET/heal/migration pays per shard),
    native vs old plane like-for-like in ONE run.

      value            RemoteStorage.read_file GiB/s over loopback
                       through a REAL GridServer — raw length-prefixed
                       frames into pooled leases, shard bytes shipped
                       drive-fd → socket via os.sendfile
      old_plane_gibps  the same fetches against a second server booted
                       under MTPU_GRID_NATIVE=off: per-chunk msgpack
                       frames read into fresh Python bytes (the
                       pre-native plane)
      vs_old_plane     value / old_plane_gibps — both columns share
                       this run's scheduler weather, so the ratio is
                       the gateable cross-run signal

    sendfile_bytes is the poller-counter delta across the measured
    native window: nonzero proves the bytes actually rode the
    zero-copy path (the section fails rather than reports a win
    otherwise, and fails if the old-plane column touches sendfile).

    Environment:
      MTPU_CLUSTER_BENCH_FETCH_MIB   shard file size (default 32,
                                     8 under MTPU_BENCH_SMALL)
    """
    try:
        _cluster_get_inner()
    except Exception as e:  # noqa: BLE001 - tiny host / boot failure
        print(json.dumps({"metric": "cluster_get_shard_fetch_gibps",
                          "value": None, "vs_old_plane": None,
                          "skip": f"{type(e).__name__}: {e}"}))


def _cluster_get_inner() -> None:
    import shutil
    import tempfile
    from concurrent.futures import ThreadPoolExecutor

    from minio_tpu.grid import loop as gloop
    from minio_tpu.grid.server import GridServer
    from minio_tpu.storage.local import LocalStorage
    from minio_tpu.storage.remote import RemoteStorage, StorageRPCService

    shard_mib = int(_os.environ.get("MTPU_CLUSTER_BENCH_FETCH_MIB", 0)
                    or (8 if _SMALL else 32))
    threads = 4                      # erasure fan-out: shards in flight
    reps = 2 if _SMALL else 4        # passes over the shard set
    one = bytes((i * 31 + 7) & 0xFF for i in range(4096))
    body = (one * ((shard_mib << 20) // len(one)))

    root = tempfile.mkdtemp(prefix="bench-cget-")
    saved = _os.environ.get("MTPU_GRID_NATIVE")
    servers = []
    try:
        drive = LocalStorage(_os.path.join(root, "d0"))
        drive.make_vol("bench")
        for t in range(threads):
            drive.create_file("bench", f"shard-{t}.bin", body)

        def measure() -> float:
            srv = GridServer(0, host="127.0.0.1")
            StorageRPCService({drive.root: drive}).register_into(srv)
            srv.start()
            servers.append(srv)
            remote = RemoteStorage("127.0.0.1", srv.port, drive.root)
            # Warm the connection + verify identity once, unmeasured.
            assert remote.read_file("bench", "shard-0.bin") == body

            def fetch(t):
                for _ in range(reps):
                    got = remote.read_file("bench", f"shard-{t}.bin")
                    assert len(got) == len(body)

            ex = ThreadPoolExecutor(max_workers=threads)
            t0 = time.perf_counter()
            list(ex.map(fetch, range(threads)))
            wall = time.perf_counter() - t0
            ex.shutdown(wait=False)
            return threads * reps * len(body) / (1 << 30) / wall

        before = gloop.stats()
        native_gibps = measure()
        mid = gloop.stats()
        sendfile_bytes = (mid["sendfile_bytes"]
                         - before["sendfile_bytes"])
        assert sendfile_bytes >= threads * reps * len(body), \
            "native fetch did not ride sendfile"

        # Old plane: fresh server under MTPU_GRID_NATIVE=off (the
        # accept loop latches the switch at boot; the client checks it
        # per call) — per-chunk msgpack frames, no raw path.
        _os.environ["MTPU_GRID_NATIVE"] = "off"
        old_gibps = measure()
        after = gloop.stats()
        assert after["sendfile_bytes"] == mid["sendfile_bytes"], \
            "old-plane column leaked onto the sendfile path"
    finally:
        if saved is None:
            _os.environ.pop("MTPU_GRID_NATIVE", None)
        else:
            _os.environ["MTPU_GRID_NATIVE"] = saved
        for srv in servers:
            try:
                srv.stop()
            except Exception:  # noqa: BLE001
                pass
        shutil.rmtree(root, ignore_errors=True)

    print(json.dumps({
        "metric": "cluster_get_shard_fetch_gibps",
        "value": round(native_gibps, 3),
        "unit": "GiB/s",
        "shard_mib": shard_mib, "threads": threads, "reps": reps,
        "sendfile_bytes": sendfile_bytes,
        "old_plane_gibps": round(old_gibps, 3),
        "vs_old_plane": round(native_gibps / max(old_gibps, 1e-9), 3),
    }))


def _rebalance() -> None:
    """Elastic fleet (ROADMAP item 3): foreground PUT/GET latency
    while a pool drains CONCURRENTLY vs the same ops on a quiescent
    layer, measured in one run — vs_quiescent (during p50 / quiescent
    p50) is the stable cross-host signal. Then the safety sweep: after
    the drain, every object (seeded + written mid-drain) must read
    back byte-identical and list exactly once (rebalance_identity
    1.0). A second, pressure-wired drain records that the migration
    governor actually yields under foreground saturation. Emits
    explicit nulls when the fixture cannot build (gate skips)."""
    try:
        _rebalance_inner()
    except (OSError, MemoryError) as e:
        print(json.dumps({"metric": "rebalance_fg_p50_during_ms",
                          "value": None, "unit": "ms",
                          "skipped": f"fixture failed: {e}"}))
        print(json.dumps({"metric": "rebalance_identity",
                          "value": None, "unit": "fraction",
                          "skipped": f"fixture failed: {e}"}))


def _rebalance_inner() -> None:
    import shutil
    import tempfile
    import threading

    from minio_tpu.object.erasure_object import ErasureSet
    from minio_tpu.object.pools import ServerPools
    from minio_tpu.object.sets import ErasureSets
    from minio_tpu.storage.local import LocalStorage

    dep = "00000000-0000-0000-0000-00000000be4c"
    rng = np.random.default_rng(7)
    base = rng.integers(0, 256, size=64 << 10, dtype=np.uint8).tobytes()

    def body_for(tag: str) -> bytes:
        return base[:-16] + tag.encode().ljust(16, b".")[:16]

    n_seed = 280 if _SMALL else 900
    fg_puts = 30 if _SMALL else 90
    fg_gets = 60 if _SMALL else 180

    def mklayer(root):
        pools = []
        for p in ("p0", "p1"):
            disks = [LocalStorage(f"{root}/{p}/d{i}") for i in range(4)]
            pools.append(ErasureSets([ErasureSet(disks)],
                                     deployment_id=dep))
        lay = ServerPools(pools)
        lay.make_bucket("bench")
        return lay

    def pctl(times: list, q: float) -> float:
        s = sorted(times)
        return round(s[min(len(s) - 1, int(len(s) * q))] * 1e3, 2)

    root = tempfile.mkdtemp(prefix="bench-rebal-")
    try:
        lay = mklayer(root)
        everything = {}
        for i in range(n_seed):
            k = f"s-{i:04d}"
            b = body_for(k)
            lay.pools[0].put_object("bench", k, b)
            everything[k] = b
        seeded = sorted(everything)

        def fg_round(tag: str) -> dict:
            put_t, get_t = [], []
            for i in range(fg_puts):
                k = f"fg-{tag}-{i:03d}"
                b = body_for(k)
                t0 = time.perf_counter()
                lay.put_object("bench", k, b)
                put_t.append(time.perf_counter() - t0)
                everything[k] = b
            for i in range(fg_gets):
                k = seeded[(i * 37) % len(seeded)]
                t0 = time.perf_counter()
                _, got = lay.get_object("bench", k)
                get_t.append(time.perf_counter() - t0)
                if got != everything[k]:
                    raise AssertionError(f"wrong bytes mid-drain: {k}")
            return {"put_p50_ms": pctl(put_t, 0.50),
                    "put_p99_ms": pctl(put_t, 0.99),
                    "get_p50_ms": pctl(get_t, 0.50),
                    "get_p99_ms": pctl(get_t, 0.99)}

        # Warmup: first reads pay one-time lazy init (caches, list
        # pool) that would land in the quiescent p99 as a fake outlier.
        for i in range(8):
            lay.get_object("bench", seeded[i])
        lay.put_object("bench", "warm", base)
        everything["warm"] = base
        quiet = fg_round("q")
        t0 = time.perf_counter()
        d = lay.start_decommission(0, checkpoint_every=64)
        during = fg_round("d")
        overlap = not d.wait(timeout=0)   # drain outlived the round?
        if not d.wait(300):
            raise AssertionError("drain never completed")
        drain_secs = time.perf_counter() - t0
        st = lay.decommission_status()
        if st["status"] != "complete" or st["failed"]:
            raise AssertionError(f"drain failed: {st}")

        # Byte-identity sweep + single-visibility over EVERYTHING.
        mismatches = 0
        for k, b in everything.items():
            _, got = lay.get_object("bench", k)
            if got != b:
                mismatches += 1
        names = []
        marker = ""
        while True:
            page = lay.list_objects("bench", marker=marker,
                                    max_keys=1000, include_versions=True)
            names.extend(o.name for o in page.objects)
            if not page.is_truncated:
                break
            marker = page.next_marker
        if len(names) != len(set(names)) or \
                set(names) != set(everything):
            mismatches += 1
        lay.close()

        # Governor-yield probe: a fresh drain wired to a saturation
        # signal must pause (yields > 0) while the foreground is busy.
        _os.environ["MTPU_REBALANCE_YIELD_MS"] = "2"
        try:
            lay2 = mklayer(f"{root}/sat")
            for i in range(40):
                lay2.pools[0].put_object("bench", f"y-{i:03d}",
                                         body_for(f"y-{i:03d}"))
            busy = threading.Event()
            busy.set()
            lay2.migration_pressure = busy.is_set
            d2 = lay2.start_decommission(0)
            deadline = time.time() + 10
            while d2.state["yields"] < 1 and time.time() < deadline:
                lay2.put_object("bench", "hot", base)   # saturating fg
            yields = int(d2.state.get("yields", 0))
            busy.clear()
            if not d2.wait(120):
                raise AssertionError("pressure-wired drain never finished")
            lay2.close()
        finally:
            _os.environ.pop("MTPU_REBALANCE_YIELD_MS", None)

        total = len(everything)
        print(json.dumps({
            "metric": "rebalance_fg_p50_during_ms",
            "value": during["put_p50_ms"],
            "unit": "ms",
            "vs_quiescent": round(during["put_p50_ms"]
                                  / max(quiet["put_p50_ms"], 1e-6), 3),
            "quiescent": quiet, "during": during,
            "drain_overlapped_measurement": overlap,
            "drain_secs": round(drain_secs, 3),
            "migrated": st.get("migrated", 0),
            "bytes_moved": st.get("bytes_moved", 0),
            "seeded_objects": n_seed, "object_bytes": len(base),
        }))
        print(json.dumps({
            "metric": "rebalance_identity",
            "value": round((total - mismatches) / total, 4),
            "unit": "fraction",
            "objects": total, "mismatches": mismatches,
            "yields_under_saturation": yields,
        }))
    finally:
        shutil.rmtree(root, ignore_errors=True)


def _replication() -> None:
    """Durable replication plane (ROADMAP item 5): enqueue-to-delivered
    lag p50/p99 under foreground PUT load through a real source->target
    server pair, with an in-run KILL-SWITCH column (the same load on a
    pair booted MTPU_REPLICATION_DURABLE=off — the v1 in-memory plane)
    so the WAL's ack-path cost is measured against its own baseline in
    the same scheduler weather. Then the chaos probe: target down,
    writes pile up (WAL + lanes + breaker), target restarts, a delete
    lands post-heal — replication_convergence is the fraction of the
    final namespace byte-identical on both sides with ZERO divergent
    extra objects (1.0 = converged). Emits explicit nulls when the
    fixture cannot build (gate skips)."""
    try:
        _replication_inner()
    except Exception as e:  # noqa: BLE001 - tiny host / boot failure
        for m in ("replication_lag_p99_ms", "replication_convergence"):
            print(json.dumps({"metric": m, "value": None,
                              "skip": f"{type(e).__name__}: {e}"}))


def _replication_inner() -> None:
    import shutil
    import sys as _sys
    import tempfile

    repo = _os.path.dirname(_os.path.abspath(__file__))
    if repo not in _sys.path:
        _sys.path.insert(0, repo)
    from minio_tpu.object.erasure_object import ErasureSet
    from minio_tpu.object.scanner import Scanner
    from minio_tpu.replication.engine import ReplicationEngine
    from minio_tpu.s3.metrics import _lag_summary
    from minio_tpu.s3.server import S3Server
    from minio_tpu.storage.local import LocalStorage
    from tests.s3client import S3Client

    n_objs = 60 if _SMALL else 240
    chaos_objs = 12 if _SMALL else 40
    rng = np.random.default_rng(7)
    base = rng.integers(0, 256, size=64 << 10, dtype=np.uint8).tobytes()

    def body_for(tag: str) -> bytes:
        return base[:-16] + tag.encode().ljust(16, b".")[:16]

    repl_xml = (b"<ReplicationConfiguration>"
                b"<Role>arn:minio:replication::r1:role</Role>"
                b"<Rule><ID>r1</ID><Status>Enabled</Status>"
                b"<Priority>1</Priority>"
                b"<DeleteMarkerReplication><Status>Enabled</Status>"
                b"</DeleteMarkerReplication>"
                b"<Destination><Bucket>arn:aws:s3:::dstb</Bucket>"
                b"</Destination></Rule></ReplicationConfiguration>")

    def build_pair(root: str):
        src_es = ErasureSet([LocalStorage(f"{root}/s{i}")
                             for i in range(4)])
        dst_es = ErasureSet([LocalStorage(f"{root}/t{i}")
                             for i in range(4)])
        src = S3Server(src_es, address="127.0.0.1:0")
        dst = S3Server(dst_es, address="127.0.0.1:0")
        src.replicator = ReplicationEngine(src_es)
        src.start()
        dst.start()
        sc, dc = S3Client(src.address), S3Client(dst.address)
        assert sc.request("PUT", "/srcb")[0] == 200
        assert dc.request("PUT", "/dstb")[0] == 200
        st, _, b = sc.request("PUT", "/minio/admin/v3/set-remote-target",
                              query={"bucket": "srcb"},
                              body=json.dumps({
                                  "endpoint": dst.address,
                                  "accessKey": "minioadmin",
                                  "secretKey": "minioadmin",
                                  "bucket": "dstb"}).encode())
        assert st == 200, b
        st, _, b = sc.request("PUT", "/srcb", query={"replication": ""},
                              body=repl_xml)
        assert st == 200, b
        return src, dst, src_es, dst_es, sc, dc

    def load_round(src, sc, prefix: str, n: int) -> tuple[dict, int]:
        """Foreground PUT load; returns (lag p50/p99 summary from the
        engine's own enqueue-to-delivered histogram, pending peak)."""
        peak = 0
        for i in range(n):
            k = f"{prefix}-{i:04d}"
            st, _, b = sc.request("PUT", f"/srcb/{k}",
                                  body=body_for(k))
            assert st == 200, b
            peak = max(peak, src.replicator.stats()["pending"])
        assert src.replicator.drain(120), "replication never drained"
        return _lag_summary(src.replicator.stats()["lag_hist"]), peak

    root = tempfile.mkdtemp(prefix="bench-repl-")
    try:
        # -- durable plane: lag under load -----------------------------
        src, dst, src_es, dst_es, sc, dc = build_pair(f"{root}/on")
        expect: dict = {}
        lag, pending_peak = load_round(src, sc, "w", n_objs)
        for i in range(n_objs):
            expect[f"w-{i:04d}"] = body_for(f"w-{i:04d}")

        # -- chaos: target dies mid-stream, restarts on the same port --
        dst_addr = dst.address
        dst.stop()
        for i in range(chaos_objs):
            k = f"c-{i:04d}"
            sc.request("PUT", f"/srcb/{k}", body=body_for(k))
            expect[k] = body_for(k)
        # stop() closed the target's object layer — the "restarted
        # process" is a fresh ErasureSet over the same drive roots.
        dst_es2 = ErasureSet([LocalStorage(f"{root}/on/t{i}")
                              for i in range(4)])
        dst2 = None
        for _ in range(40):            # port may linger in TIME_WAIT
            try:
                dst2 = S3Server(dst_es2, address=dst_addr)
                dst2.start()
                break
            except OSError:
                time.sleep(0.25)
        assert dst2 is not None, "target could not rebind its port"
        dc = S3Client(dst_addr)
        st, _, _ = sc.request("DELETE", f"/srcb/w-0000")
        assert st in (200, 204)
        expect["w-0000"] = None

        # Converge: lanes retry off the timer heap; the scanner pass is
        # the production safety net re-driving anything that went
        # terminal-FAILED while the target was dark.
        scanner = Scanner([src_es], throttle=0)
        scanner.on_object.append(src.replicator.scanner_hook)
        live = {k.encode() for k, v in expect.items() if v is not None}
        deadline = time.monotonic() + (120 if _SMALL else 180)
        matched, extras = 0, 0
        while time.monotonic() < deadline:
            scanner.scan_cycle()
            src.replicator.drain(10)
            st, _, body = dc.request("GET", "/dstb",
                                     query={"max-keys": "1000"})
            assert st == 200, body
            import re as _re
            on_tgt = set(_re.findall(rb"<Key>([^<]+)</Key>", body))
            extras = len(on_tgt - live)
            matched = 0
            for k, want in expect.items():
                st, _, got = dc.request("GET", f"/dstb/{k}")
                if (want is None and st == 404) or \
                        (want is not None and st == 200 and got == want):
                    matched += 1
            if matched == len(expect) and extras == 0:
                break
            time.sleep(0.5)
        convergence = matched / len(expect)
        if extras:                     # divergent objects cap the score
            convergence = min(convergence, 0.99)
        src.replicator.stop()
        src.stop()
        dst2.stop()

        # -- kill-switch column: v1 in-memory plane, same load ---------
        saved = _os.environ.get("MTPU_REPLICATION_DURABLE")
        _os.environ["MTPU_REPLICATION_DURABLE"] = "off"
        try:
            osrc, odst, _, _, osc, _ = build_pair(f"{root}/off")
            off_lag, _ = load_round(osrc, osc, "w", n_objs)
            osrc.replicator.stop()
            osrc.stop()
            odst.stop()
        finally:
            if saved is None:
                _os.environ.pop("MTPU_REPLICATION_DURABLE", None)
            else:
                _os.environ["MTPU_REPLICATION_DURABLE"] = saved

        print(json.dumps({
            "metric": "replication_lag_p99_ms",
            "value": lag["p99_ms"],
            "unit": "ms",
            "p50_ms": lag["p50_ms"],
            "mean_ms": lag["mean_ms"],
            "delivered": lag["count"],
            "pending_peak": pending_peak,
            "objects": n_objs, "object_bytes": len(base),
            "durable_off_p99_ms": off_lag["p99_ms"],
            "durable_off_p50_ms": off_lag["p50_ms"],
            "vs_durable_off": round(lag["p99_ms"]
                                    / max(off_lag["p99_ms"], 1e-6), 3),
        }))
        print(json.dumps({
            "metric": "replication_convergence",
            "value": round(convergence, 4),
            "unit": "fraction",
            "objects": len(expect),
            "divergent": extras,
            "chaos": "target kill/restart mid-stream + post-heal delete",
        }))
    finally:
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    import sys as _sys
    if "--serve-probe" in _sys.argv:
        _serve_probe()
    elif "--scaling-probe" in _sys.argv:
        _scaling_probe()
    elif "--get-scaling-probe" in _sys.argv:
        _get_scaling_probe()
    else:
        main()
